"""FUSE mount e2e: real kernel VFS ops through /dev/fuse against an
in-process cluster (reference: weed/mount/weedfs.go + its filehandle
suite).  The filesystem ops run in a worker thread while the asyncio
loop serves the FUSE requests — same-process mounts deadlock otherwise.
"""
import asyncio
import os

import aiohttp
import pytest

pytestmark = pytest.mark.skipif(
    not os.path.exists("/dev/fuse") or os.geteuid() != 0,
    reason="needs /dev/fuse and root",
)

from seaweedfs_tpu.server.cluster import LocalCluster  # noqa: E402
from seaweedfs_tpu.mount import Mount  # noqa: E402


def run(coro):
    return asyncio.run(coro)


async def mounted(tmp_path):
    mnt = str(tmp_path / "mnt")
    os.makedirs(mnt)
    cluster = LocalCluster(
        base_dir=str(tmp_path / "data"), n_volume_servers=1, with_filer=True
    )
    await cluster.start()
    m = Mount(
        mnt,
        filer_address=cluster.filer.url,
        filer_grpc_address=f"{cluster.filer.ip}:{cluster.filer.grpc_port}",
    )
    await m.start()
    return cluster, m, mnt


def test_mount_posix_ops(tmp_path):
    async def go():
        cluster, m, mnt = await mounted(tmp_path)
        try:
            blob = os.urandom(300_000)

            def fsops():
                os.makedirs(mnt + "/a/b")
                with open(mnt + "/a/b/f.bin", "wb") as f:
                    f.write(blob)
                st = os.stat(mnt + "/a/b/f.bin")
                assert st.st_size == len(blob)
                with open(mnt + "/a/b/f.bin", "rb") as f:
                    assert f.read() == blob
                with open(mnt + "/a/b/f.bin", "rb") as f:
                    f.seek(123_456)
                    assert f.read(1000) == blob[123_456:124_456]
                assert os.listdir(mnt + "/a") == ["b"]
                # append via O_APPEND-style read-modify-write
                with open(mnt + "/a/b/f.bin", "ab") as f:
                    f.write(b"tail")
                assert os.stat(mnt + "/a/b/f.bin").st_size == len(blob) + 4
                # rename across directories
                os.makedirs(mnt + "/c")
                os.rename(mnt + "/a/b/f.bin", mnt + "/c/g.bin")
                assert not os.path.exists(mnt + "/a/b/f.bin")
                with open(mnt + "/c/g.bin", "rb") as f:
                    assert f.read() == blob + b"tail"
                # truncate
                with open(mnt + "/c/g.bin", "r+b") as f:
                    f.truncate(10)
                assert os.stat(mnt + "/c/g.bin").st_size == 10
                os.remove(mnt + "/c/g.bin")
                with pytest.raises(OSError):
                    os.rmdir(mnt + "/a")  # not empty (has b)
                os.rmdir(mnt + "/a/b")
                os.rmdir(mnt + "/a")
                os.rmdir(mnt + "/c")
                assert os.listdir(mnt) == []

            await asyncio.wait_for(asyncio.to_thread(fsops), 60)
        finally:
            await m.stop()
            await cluster.stop()

    run(go())


def test_mount_preserves_mode_and_zero_fills_truncate(tmp_path):
    async def go():
        cluster, m, mnt = await mounted(tmp_path)
        try:
            def fsops():
                p = mnt + "/script.sh"
                with open(p, "w") as f:
                    f.write("#!/bin/sh\necho hi\n")
                os.chmod(p, 0o755)
                assert os.stat(p).st_mode & 0o777 == 0o755
                # a write+close must not clobber the mode back to default
                with open(p, "a") as f:
                    f.write("echo more\n")
                assert os.stat(p).st_mode & 0o777 == 0o755, oct(
                    os.stat(p).st_mode
                )
                # truncate-grow without an open handle zero-fills (POSIX)
                q = mnt + "/grow.bin"
                with open(q, "wb") as f:
                    f.write(b"abc")
                os.truncate(q, 10)
                assert os.stat(q).st_size == 10
                with open(q, "rb") as f:
                    assert f.read() == b"abc" + b"\x00" * 7

            await asyncio.wait_for(asyncio.to_thread(fsops), 60)
        finally:
            await m.stop()
            await cluster.stop()

    run(go())


def test_mount_sees_filer_writes_and_vice_versa(tmp_path):
    async def go():
        cluster, m, mnt = await mounted(tmp_path)
        try:
            base = f"http://{cluster.filer.url}"
            async with aiohttp.ClientSession() as s:
                async with s.put(base + "/shared/from_http.txt", data=b"via http"):
                    pass

            def read_it():
                with open(mnt + "/shared/from_http.txt", "rb") as f:
                    return f.read()

            assert await asyncio.wait_for(asyncio.to_thread(read_it), 30) == b"via http"

            def write_it():
                with open(mnt + "/shared/from_fuse.txt", "wb") as f:
                    f.write(b"via fuse")

            await asyncio.wait_for(asyncio.to_thread(write_it), 30)
            async with aiohttp.ClientSession() as s:
                async with s.get(base + "/shared/from_fuse.txt") as r:
                    assert r.status == 200
                    assert await r.read() == b"via fuse"
        finally:
            await m.stop()
            await cluster.stop()

    run(go())


def test_mount_xattrs(tmp_path):
    async def go():
        cluster, m, mnt = await mounted(tmp_path)
        try:
            def fsops():
                p = mnt + "/x.txt"
                with open(p, "wb") as f:
                    f.write(b"data")
                os.setxattr(p, "user.color", b"blue")
                os.setxattr(p, "user.shape", b"round")
                assert os.getxattr(p, "user.color") == b"blue"
                assert sorted(os.listxattr(p)) == ["user.color", "user.shape"]
                os.setxattr(p, "user.color", b"red")  # overwrite
                assert os.getxattr(p, "user.color") == b"red"
                os.removexattr(p, "user.shape")
                assert os.listxattr(p) == ["user.color"]
                with pytest.raises(OSError):
                    os.getxattr(p, "user.shape")
                with pytest.raises(OSError):
                    os.removexattr(p, "user.absent")

            await asyncio.wait_for(asyncio.to_thread(fsops), 60)
        finally:
            await m.stop()
            await cluster.stop()

    run(go())


def test_mount_hard_links(tmp_path):
    async def go():
        cluster, m, mnt = await mounted(tmp_path)
        try:
            blob = os.urandom(200_000)

            def fsops():
                a = mnt + "/orig.bin"
                b = mnt + "/linked.bin"
                with open(a, "wb") as f:
                    f.write(blob)
                os.link(a, b)
                with open(b, "rb") as f:
                    assert f.read() == blob
                # shared inode: writes through ONE name are visible
                # through the other
                with open(a, "wb") as f:
                    f.write(b"rewritten-via-a")
                with open(b, "rb") as f:
                    assert f.read() == b"rewritten-via-a"
                # xattrs ride the shared inode too
                os.setxattr(a, "user.tag", b"shared")
                assert os.getxattr(b, "user.tag") == b"shared"
                # restore big content for the filer-side check below
                with open(a, "wb") as f:
                    f.write(blob)
                # deleting ONE name must not GC the shared chunks
                os.remove(a)
                with open(b, "rb") as f:
                    assert f.read() == blob

            await asyncio.wait_for(asyncio.to_thread(fsops), 60)
            # the surviving name still reads through the filer (chunks
            # intact on the volume servers, not just cached)
            cluster.filer.chunk_cache.clear() if hasattr(
                cluster.filer.chunk_cache, "clear"
            ) else None
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{cluster.filer.url}/linked.bin"
                ) as r:
                    assert r.status == 200
                    assert await r.read() == blob

            def fsops2():
                # removing the LAST name releases the data
                os.remove(mnt + "/linked.bin")
                assert not os.path.exists(mnt + "/linked.bin")

            await asyncio.wait_for(asyncio.to_thread(fsops2), 60)
        finally:
            await m.stop()
            await cluster.stop()

    run(go())


def test_mount_wb_overwrite_truncates(tmp_path):
    """Reopening an existing file with open('wb') must truncate: the
    kernel's no-fh SETATTR size=0 used to race the first WRITE's spool
    seeding and resurrect the old tail on flush."""

    async def go():
        cluster, m, mnt = await mounted(tmp_path)
        try:
            blob = os.urandom(100_000)

            def fsops():
                p = mnt + "/over.bin"
                with open(p, "wb") as f:
                    f.write(blob)
                with open(p, "wb") as f:
                    f.write(b"short")
                assert os.stat(p).st_size == 5
                with open(p, "rb") as f:
                    assert f.read() == b"short"

            await asyncio.wait_for(asyncio.to_thread(fsops), 60)
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{cluster.filer.url}/over.bin"
                ) as r:
                    assert await r.read() == b"short"
        finally:
            await m.stop()
            await cluster.stop()

    run(go())


def test_mount_streaming_write_bounded_memory(tmp_path):
    """A file much larger than the dirty-page budget streams out as
    chunks while being written: resident buffers stay bounded at
    max_resident x chunk_size (VERDICT round-2 'done' condition for the
    FUSE write pipeline)."""

    async def go():
        mnt = str(tmp_path / "mnt")
        os.makedirs(mnt)
        cluster = LocalCluster(
            base_dir=str(tmp_path / "data"), n_volume_servers=1,
            with_filer=True,
        )
        await cluster.start()
        m = Mount(
            mnt,
            filer_address=cluster.filer.url,
            filer_grpc_address=f"{cluster.filer.ip}:{cluster.filer.grpc_port}",
            chunk_size=256 * 1024,
            max_resident_chunks=2,
        )
        await m.start()
        try:
            import hashlib
            import random

            total = 8 * 1024 * 1024  # 32x the 512KB resident budget
            rng = random.Random(7)
            digest = hashlib.sha256()
            created = []
            orig_pages = m.fs._pages

            def tracking(h, base_size=0):
                p = orig_pages(h, base_size)
                if p not in created:
                    created.append(p)
                return p

            m.fs._pages = tracking

            def write_big():
                with open(mnt + "/big.bin", "wb") as f:
                    remaining = total
                    while remaining:
                        piece = rng.randbytes(min(128 * 1024, remaining))
                        digest.update(piece)
                        f.write(piece)
                        remaining -= len(piece)

            await asyncio.wait_for(asyncio.to_thread(write_big), 120)
            m.fs._pages = orig_pages
            assert created, "write path never built dirty pages"
            # resident buffers never exceeded budget+1 (the chunk being
            # written) despite the file being 32x larger
            assert all(p.max_resident_seen <= 3 for p in created), [
                p.max_resident_seen for p in created
            ]

            def read_back():
                got = hashlib.sha256()
                with open(mnt + "/big.bin", "rb") as f:
                    while True:
                        piece = f.read(1 << 20)
                        if not piece:
                            break
                        got.update(piece)
                return got.hexdigest()

            assert (
                await asyncio.wait_for(asyncio.to_thread(read_back), 120)
                == digest.hexdigest()
            )
            # the filer holds it as many chunks, none bigger than the limit
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{cluster.filer.url}/big.bin?metadata=true"
                ) as r:
                    pass  # metadata view optional; size check via HEAD
                async with s.head(f"http://{cluster.filer.url}/big.bin") as r:
                    assert int(r.headers.get("Content-Length", 0)) == total
        finally:
            await m.stop()
            await cluster.stop()

    run(go())


def test_mount_random_write_seeds_only_straddled_chunks(tmp_path):
    """A small random write into a big existing file downloads only the
    chunk(s) it straddles — not the whole file (VERDICT: 'seed only the
    ranges a random write straddles')."""

    async def go():
        mnt = str(tmp_path / "mnt")
        os.makedirs(mnt)
        cluster = LocalCluster(
            base_dir=str(tmp_path / "data"), n_volume_servers=1,
            with_filer=True,
        )
        await cluster.start()
        chunk = 256 * 1024
        m = Mount(
            mnt,
            filer_address=cluster.filer.url,
            filer_grpc_address=f"{cluster.filer.ip}:{cluster.filer.grpc_port}",
            chunk_size=chunk,
            max_resident_chunks=2,
        )
        await m.start()
        try:
            blob = bytearray(os.urandom(4 * 1024 * 1024))

            def write_orig():
                with open(mnt + "/r.bin", "wb") as f:
                    f.write(blob)

            await asyncio.wait_for(asyncio.to_thread(write_orig), 60)

            # count range-read traffic during the random write
            reads = []
            real = m.fs._read_range

            async def counting(path, offset, size):
                reads.append((offset, size))
                return await real(path, offset, size)

            m.fs._read_range = counting
            patch = os.urandom(1000)
            at = 2 * chunk + 12345  # inside chunk 2, straddling nothing else

            def write_patch():
                with open(mnt + "/r.bin", "r+b") as f:
                    f.seek(at)
                    f.write(patch)

            await asyncio.wait_for(asyncio.to_thread(write_patch), 60)
            m.fs._read_range = real
            blob[at : at + len(patch)] = patch
            seeded = sum(size for _, size in reads)
            assert seeded <= 2 * chunk, f"seeded {seeded} bytes: {reads}"

            def read_back():
                with open(mnt + "/r.bin", "rb") as f:
                    return f.read()

            got = await asyncio.wait_for(asyncio.to_thread(read_back), 60)
            assert got == bytes(blob)
        finally:
            await m.stop()
            await cluster.stop()

    run(go())


def test_two_mounts_rename_visibility(tmp_path):
    """A second mount's meta cache sees a first mount's rename within one
    meta-log tick (the SubscribeMetadata invalidation path; reference
    mount/meta_cache_subscribe.go)."""

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path / "data"), n_volume_servers=1,
            with_filer=True,
        )
        await cluster.start()
        mnts = []
        mounts = []
        for i in (1, 2):
            mnt = str(tmp_path / f"mnt{i}")
            os.makedirs(mnt)
            m = Mount(
                mnt,
                filer_address=cluster.filer.url,
                filer_grpc_address=(
                    f"{cluster.filer.ip}:{cluster.filer.grpc_port}"
                ),
                meta_ttl=3600.0,  # cache would stay stale for an hour
            )                     # without subscription invalidation
            await m.start()
            mnts.append(mnt)
            mounts.append(m)
        try:
            def seed():
                with open(mnts[0] + "/old.txt", "wb") as f:
                    f.write(b"payload")

            await asyncio.wait_for(asyncio.to_thread(seed), 60)

            # warm mount 2's cache with the pre-rename state
            def warm():
                assert os.listdir(mnts[1]) == ["old.txt"]
                assert os.path.exists(mnts[1] + "/old.txt")

            await asyncio.wait_for(asyncio.to_thread(warm), 60)
            assert mounts[1].fs.meta.get_listing("/") is not None

            def rename():
                os.rename(mnts[0] + "/old.txt", mnts[0] + "/new.txt")

            await asyncio.wait_for(asyncio.to_thread(rename), 60)

            # within one meta-log tick the second mount reflects it
            deadline = asyncio.get_event_loop().time() + 10
            while True:
                def view():
                    return sorted(os.listdir(mnts[1]))

                names = await asyncio.wait_for(asyncio.to_thread(view), 60)
                if names == ["new.txt"]:
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(f"mount2 still sees {names}")
                await asyncio.sleep(0.2)

            def read_new():
                with open(mnts[1] + "/new.txt", "rb") as f:
                    return f.read()

            assert await asyncio.wait_for(
                asyncio.to_thread(read_new), 60
            ) == b"payload"
        finally:
            for m in mounts:
                await m.stop()
            await cluster.stop()

    run(go())


def test_mount_http_ops_retry_transient_5xx():
    """A transient filer 500 must not surface as EIO to the kernel on the
    first attempt: the mount's idempotent HTTP ops retry briefly (network
    filesystem semantics), failing only when the error persists."""
    import aiohttp.web as web

    from seaweedfs_tpu.mount import fusekernel as fk
    from seaweedfs_tpu.mount.weedfs import WeedFS

    async def go():
        fails = {"get": 1, "put": 2}  # transient: recover within retries
        body = b"retry-me"

        async def h_get(request):
            if fails["get"] > 0:
                fails["get"] -= 1
                return web.Response(status=500)
            return web.Response(body=body)

        async def h_put(request):
            if fails["put"] > 0:
                fails["put"] -= 1
                return web.Response(status=503)
            return web.Response()

        app = web.Application()
        app.router.add_get("/f.bin", h_get)
        app.router.add_put("/f.bin", h_put)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        fs = WeedFS(f"127.0.0.1:{port}")
        try:
            got = await fs._read_range("/f.bin", 0, 0)
            assert got == body  # recovered after one 500
            await fs._put("/f.bin", body)  # recovered after two 503s
            assert fails == {"get": 0, "put": 0}

            # a PERSISTENT failure still raises EIO after the retries
            fails["put"] = 99
            try:
                await fs._put("/f.bin", body)
                raise AssertionError("persistent 503 did not raise")
            except fk.FuseError as e:
                import errno as errno_mod

                assert e.errno_value == errno_mod.EIO
        finally:
            if fs._session is not None:
                await fs._session.close()
            await runner.cleanup()

    run(go())
