"""DirtyPages unit tests against a fake filer: the chunked write
pipeline's edge cases (eviction, rewrite-after-eviction, seeding,
read-your-writes, truncation) without a kernel mount."""
import asyncio
import os

from seaweedfs_tpu.mount.pages import DirtyPages


class FakeFS:
    """Emulates the filer surface DirtyPages drives: committed content is
    a flat buffer; chunks apply in commit order (ts order equivalent,
    since each commit appends newer chunks)."""

    def __init__(self):
        self.blobs: dict[str, bytes] = {}
        self.committed = bytearray()
        self.size = 0
        self.next_fid = 0
        self.reads: list[tuple[int, int]] = []
        self.commits = 0

    async def _read_range(self, path, offset, size):
        self.reads.append((offset, size))
        end = min(self.size, offset + size)
        view = bytes(self.committed[offset:end])
        return view + b"\x00" * (min(size, self.size - offset) - len(view))

    async def _assign_upload(self, data):
        fid = f"f{self.next_fid}"
        self.next_fid += 1
        self.blobs[fid] = bytes(data)
        return fid

    async def _commit_entry(self, path, chunks, size):
        self.commits += 1
        for c in chunks:
            blob = self.blobs[c.file_id]
            end = c.offset + len(blob)
            if len(self.committed) < end:
                self.committed.extend(b"\x00" * (end - len(self.committed)))
            self.committed[c.offset : end] = blob
        self.size = size
        if len(self.committed) < size:
            self.committed.extend(b"\x00" * (size - len(self.committed)))
        del self.committed[size:]

    async def _truncate_entry(self, path, new_size):
        self.size = new_size
        del self.committed[new_size:]


def run(coro):
    return asyncio.run(coro)


CS = 1024


def make(base=b""):
    fs = FakeFS()
    fs.committed = bytearray(base)
    fs.size = len(base)
    pages = DirtyPages(fs, "/f", len(base), chunk_size=CS, max_resident=2)
    return fs, pages


def test_sequential_write_evicts_and_flushes():
    async def go():
        fs, p = make()
        blob = os.urandom(6 * CS + 123)
        for off in range(0, len(blob), 300):
            await p.write(off, blob[off : off + 300])
        assert p.max_resident_seen <= 3
        await p.flush()
        assert bytes(fs.committed) == blob
        assert fs.size == len(blob)

    run(go())


def test_rewrite_of_evicted_uncommitted_chunk():
    """Regression: a partial write into a chunk that was evicted and
    uploaded (but not committed) must first publish the upload, then
    seed from it — not shadow it with zeros."""

    async def go():
        fs, p = make()
        blob = bytearray(os.urandom(4 * CS))
        await p.write(0, bytes(blob))  # fills chunks 0-3, evicting 0-1
        assert p.uploaded, "eviction should have uploaded chunks"
        patch = b"PATCH!"
        await p.write(100, patch)  # back into evicted chunk 0
        blob[100 : 100 + len(patch)] = patch
        await p.flush()
        assert bytes(fs.committed) == bytes(blob)

    run(go())


def test_partial_write_seeds_only_straddled_chunk():
    async def go():
        base = os.urandom(8 * CS)
        fs, p = make(base)
        await p.write(3 * CS + 10, b"xy")
        seeded = sum(size for _, size in fs.reads)
        assert seeded <= CS, fs.reads
        await p.flush()
        expect = bytearray(base)
        expect[3 * CS + 10 : 3 * CS + 12] = b"xy"
        assert bytes(fs.committed) == bytes(expect)

    run(go())


def test_read_your_writes_before_flush():
    async def go():
        base = os.urandom(3 * CS)
        fs, p = make(base)
        await p.write(CS + 5, b"hello")
        got = await p.read(CS, 16)
        expect = bytearray(base[CS : CS + 16])
        expect[5:10] = b"hello"
        assert got == bytes(expect)
        # spanning read across resident + committed
        got = await p.read(0, 3 * CS)
        full = bytearray(base)
        full[CS + 5 : CS + 10] = b"hello"
        assert got == bytes(full)

    run(go())


def test_write_beyond_eof_reads_zeros_in_hole():
    async def go():
        fs, p = make(b"abc")
        await p.write(2 * CS, b"tail")
        assert p.size == 2 * CS + 4
        got = await p.read(0, p.size)
        expect = b"abc" + b"\x00" * (2 * CS - 3) + b"tail"
        assert got == expect
        await p.flush()
        assert bytes(fs.committed) == expect

    run(go())


def test_truncate_paths():
    async def go():
        base = os.urandom(2 * CS)
        fs, p = make(base)
        await p.write(10, b"zzz")
        await p.truncate(CS)  # shrink: publish then cut
        assert p.size == CS
        await p.flush()
        expect = bytearray(base[:CS])
        expect[10:13] = b"zzz"
        assert bytes(fs.committed) == bytes(expect)
        await p.truncate(CS + 50)  # growth: zeros
        await p.flush()
        assert fs.size == CS + 50
        p.truncate_zero()
        assert p.size == 0

    run(go())
