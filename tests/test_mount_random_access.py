"""Randomized random-access read/write harness over the REAL kernel mount
(the role of the reference's test/random_access Java harness): interleaved
positional writes, reads, truncates, and reopens against an in-memory
oracle, verifying byte-exactness after every operation batch.
"""
import asyncio
import os
import random

import pytest

pytestmark = pytest.mark.skipif(
    not os.path.exists("/dev/fuse") or os.geteuid() != 0,
    reason="needs /dev/fuse and root",
)

from seaweedfs_tpu.mount import Mount  # noqa: E402
from seaweedfs_tpu.server.cluster import LocalCluster  # noqa: E402


def test_randomized_positional_io(tmp_path):
    async def go():
        mnt = str(tmp_path / "mnt")
        os.makedirs(mnt)
        cluster = LocalCluster(
            base_dir=str(tmp_path / "data"), n_volume_servers=1,
            with_filer=True,
        )
        await cluster.start()
        m = Mount(
            mnt,
            filer_address=cluster.filer.url,
            filer_grpc_address=f"{cluster.filer.ip}:{cluster.filer.grpc_port}",
            chunk_size=64 * 1024,  # small chunks: more boundaries per op
        )
        await m.start()
        try:
            def harness():
                rng = random.Random(1234)
                path = mnt + "/ra.bin"
                size_cap = 1 << 20
                oracle = bytearray()
                fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
                try:
                    for step in range(120):
                        op = rng.randrange(10)
                        if op < 5:  # positional write
                            off = rng.randrange(0, size_cap)
                            n = rng.randrange(1, 64 * 1024)
                            blob = rng.randbytes(n)
                            os.pwrite(fd, blob, off)
                            if len(oracle) < off + n:
                                oracle.extend(
                                    b"\x00" * (off + n - len(oracle))
                                )
                            oracle[off : off + n] = blob
                        elif op < 8:  # positional read
                            if not oracle:
                                continue
                            off = rng.randrange(0, len(oracle))
                            n = rng.randrange(1, 96 * 1024)
                            got = os.pread(fd, n, off)
                            want = bytes(oracle[off : off + n])
                            assert got == want, (
                                f"step {step}: read {len(got)}B@{off} "
                                "diverged from oracle"
                            )
                        elif op < 9 and oracle:  # truncate (shrink or grow)
                            new = rng.randrange(0, len(oracle) + 4096)
                            os.ftruncate(fd, new)
                            if new <= len(oracle):
                                del oracle[new:]
                            else:
                                oracle.extend(b"\x00" * (new - len(oracle)))
                        else:  # flush + reopen: durability through commit
                            os.close(fd)
                            fd = os.open(path, os.O_RDWR)
                            st = os.stat(path)
                            assert st.st_size == len(oracle), (
                                f"step {step}: size {st.st_size} != "
                                f"oracle {len(oracle)}"
                            )
                    os.close(fd)
                    fd = -1
                    with open(path, "rb") as f:
                        assert f.read() == bytes(oracle), "final content"
                finally:
                    if fd >= 0:
                        os.close(fd)

            await asyncio.to_thread(harness)
        finally:
            await m.stop()
            await cluster.stop()

    asyncio.run(go())
