"""MQ broker e2e: topic configure, partitioned publish, replay +
tail subscribe, consumer-group offset resume, broker restart recovery
from filer-persisted logs.

Reference shapes: weed/mq/broker/ + client/pub_client/sub_client.
"""
import asyncio

import pytest

from seaweedfs_tpu.mq import MessageQueueBroker, MqClient
from seaweedfs_tpu.server.cluster import LocalCluster


def run(coro):
    return asyncio.run(coro)


async def make(tmp_path, masters=None):
    cluster = LocalCluster(
        base_dir=str(tmp_path), n_volume_servers=1, with_filer=True
    )
    await cluster.start()
    broker = MessageQueueBroker(
        filer_address=cluster.filer.url,
        filer_grpc_address=f"{cluster.filer.ip}:{cluster.filer.grpc_port}",
        port=0,
        masters=(
            [cluster.master.advertise_url] if masters == "cluster" else masters
        ),
    )
    await broker.start()
    return cluster, broker


def test_mq_pubsub_and_groups(tmp_path):
    async def go():
        cluster, broker = await make(tmp_path)
        try:
            c = MqClient(broker.grpc_url)
            topic = c.topic("events")
            assert await c.configure_topic(topic, partition_count=4) == 4
            topics = await c.list_topics()
            assert [(t.name, n) for t, n in topics] == [("events", 4)]

            msgs = [
                (f"user{i % 7}".encode(), f"event-{i}".encode())
                for i in range(100)
            ]
            placed = await c.publish(topic, msgs)
            assert len(placed) == 100
            # same key -> same partition, offsets strictly increasing
            by_key: dict[bytes, list[tuple[int, int]]] = {}
            for (key, _), po in zip(msgs, placed):
                by_key.setdefault(key, []).append(po)
            for key, pos in by_key.items():
                assert len({p for p, _ in pos}) == 1, f"{key} split partitions"
                offsets = [o for _, o in pos]
                assert offsets == sorted(offsets)

            # replay every partition: all 100 messages, in-partition order
            got = []
            for part in range(4):
                prev = -1
                async for offset, key, value in c.subscribe(topic, part):
                    assert offset > prev
                    prev = offset
                    got.append((key, value))
            assert sorted(got) == sorted(msgs)

            # consumer group: read 2 from partition 0, commit, resume
            first = []
            async for offset, key, value in c.subscribe(
                topic, 0, consumer_group="g1"
            ):
                first.append((offset, key, value))
                if len(first) == 2:
                    break
            await c.commit(topic, 0, "g1", first[-1][0] + 1)
            resumed = []
            async for offset, key, value in c.subscribe(
                topic, 0, consumer_group="g1"
            ):
                resumed.append(offset)
            assert resumed and resumed[0] == first[-1][0] + 1

            # tail: a live subscriber sees messages published after it starts
            seen = asyncio.Event()
            tail_got = []

            async def tailer():
                async for offset, key, value in c.subscribe(
                    topic, 1, start_offset=-2, tail=True
                ):
                    tail_got.append(value)
                    seen.set()
                    return

            task = asyncio.create_task(tailer())
            await asyncio.sleep(0.2)
            await c.publish(topic, [(b"", b"live-msg")], partition=1)
            await asyncio.wait_for(seen.wait(), 10)
            task.cancel()
            assert tail_got == [b"live-msg"]
        finally:
            await broker.stop()
            await cluster.stop()

    run(go())


def test_mq_broker_restart_recovers_log(tmp_path):
    async def go():
        cluster, broker = await make(tmp_path)
        try:
            c = MqClient(broker.grpc_url)
            topic = c.topic("durable")
            await c.configure_topic(topic, partition_count=2)
            msgs = [(b"k%d" % i, b"v%d" % i) for i in range(30)]
            await c.publish(topic, msgs)
            await broker.stop()  # final flush persists via the filer

            broker2 = MessageQueueBroker(
                filer_address=cluster.filer.url,
                filer_grpc_address=f"{cluster.filer.ip}:{cluster.filer.grpc_port}",
                port=0,
            )
            await broker2.start()
            try:
                c2 = MqClient(broker2.grpc_url)
                topics = await c2.list_topics()
                assert [(t.name, n) for t, n in topics] == [("durable", 2)]
                got = []
                for part in range(2):
                    async for _, key, value in c2.subscribe(topic, part):
                        got.append((key, value))
                assert sorted(got) == sorted(msgs)
                # offsets continue after the recovered tail — no reuse
                placed = await c2.publish(topic, [(b"k0", b"after-restart")])
                part, off = placed[0]
                replay = []
                async for o, _, v in c2.subscribe(topic, part):
                    replay.append((o, v))
                assert replay[-1] == (off, b"after-restart")
                assert len({o for o, _ in replay}) == len(replay), "offset reuse"
            finally:
                await broker2.stop()
        finally:
            await cluster.stop()

    run(go())


def test_broker_registers_with_master(tmp_path):
    async def go():
        cluster, broker = await make(tmp_path, masters="cluster")
        try:
            from seaweedfs_tpu.pb import master_pb2

            from seaweedfs_tpu.pb import server_address

            async def brokers():
                resp = await cluster.master.ListClusterNodes(
                    master_pb2.ListClusterNodesRequest(client_type="broker"),
                    None,
                )
                # registry rows are host:port[.grpc]; dialable via
                # grpc_address like every other registrant
                return [
                    server_address.grpc_address(n.address)
                    for n in resp.cluster_nodes
                ]

            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if broker.grpc_url in await brokers():
                    break
                await asyncio.sleep(0.1)
            assert broker.grpc_url in await brokers()
        finally:
            await broker.stop()
            await cluster.stop()

    run(go())


def test_balancer_seam_routes_partitions(tmp_path):
    """Partition->broker assignment goes through the balancer interface:
    a fake two-broker assignment makes this broker refuse the partitions
    it doesn't own and advertise the owner in lookups (reference
    mq/broker/balancer as a seam, not a hardcoded self-answer)."""

    async def go():
        cluster, broker = await make(tmp_path)

        class TwoBrokerBalancer:
            """Even partitions live here, odd ones on a phantom peer."""

            def __init__(self, local):
                self.local = local

            def broker_for(self, tkey, partition, partition_count):
                return self.local if partition % 2 == 0 else "other:19999"

            def brokers_for_topic(self, tkey, n):
                return [self.broker_for(tkey, i, n) for i in range(n)]

        try:
            from seaweedfs_tpu.mq.client import MqClient

            client = MqClient(broker.grpc_url)
            topic = MqClient.topic("t", "ns")
            await client.configure_topic(topic, partition_count=2)
            broker._balancer = TwoBrokerBalancer(broker.grpc_url)

            # lookup advertises the per-partition assignment
            from seaweedfs_tpu.pb import Stub, mq_pb2
            from seaweedfs_tpu.pb.rpc import channel

            stub = Stub(channel(broker.grpc_url), mq_pb2, "SeaweedMessaging")
            resp = await stub.LookupTopicBrokers(
                mq_pb2.LookupTopicBrokersRequest(topic=topic)
            )
            assert list(resp.partition_brokers) == [
                broker.grpc_url, "other:19999",
            ]

            # publishing to the owned partition works; the foreign one is
            # refused with the owner named
            out = await client.publish(topic, [(b"k", b"v")], partition=0)
            assert out == [(0, 0)]
            with pytest.raises(RuntimeError) as ei:
                await client.publish(topic, [(b"k", b"v")], partition=1)
            assert "other:19999" in str(ei.value)
        finally:
            await broker.stop()
            await cluster.stop()

    run(go())


async def _wait_brokers(broker, n, timeout=8.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        await broker.balancer.refresh()
        if len(broker.balancer._brokers) == n:
            return
        await asyncio.sleep(0.2)
    raise AssertionError(
        f"registry never converged to {n} brokers: {broker.balancer._brokers}"
    )


def test_multi_broker_assignment_and_failover(tmp_path):
    """TWO live brokers: partitions split across both via the registry
    balancer, lookups agree from either broker, publish_routed reaches the
    owners cross-broker, and killing one broker reassigns its partitions
    to the survivor, which recovers their filer-persisted logs."""

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, with_filer=True
        )
        await cluster.start()
        masters = [cluster.master.advertise_url]

        def mk():
            return MessageQueueBroker(
                filer_address=cluster.filer.url,
                filer_grpc_address=f"{cluster.filer.ip}:{cluster.filer.grpc_port}",
                port=0,
                masters=masters,
            )

        b1, b2 = mk(), mk()
        await b1.start()
        await b2.start()
        b2_stopped = False
        try:
            await _wait_brokers(b1, 2)
            await _wait_brokers(b2, 2)

            c1 = MqClient(b1.grpc_url)
            topic = MqClient.topic("ev")
            await c1.configure_topic(topic, partition_count=4)
            count, brokers = await c1.lookup(topic)
            assert count == 4
            assert set(brokers) == {b1.grpc_url, b2.grpc_url}, (
                "partitions must spread across BOTH live brokers"
            )
            # both brokers answer the same assignment (lazy topic discovery
            # on b2, which never saw the ConfigureTopic)
            c2 = MqClient(b2.grpc_url)
            assert (await c2.lookup(topic))[1] == brokers

            msgs = [(f"k{i}".encode(), f"v{i}".encode()) for i in range(40)]
            assert await c1.publish_routed(topic, msgs) == 40

            # direct publish to a foreign partition is refused, owner named
            foreign = next(
                i for i, a in enumerate(brokers) if a != b1.grpc_url
            )
            with pytest.raises(RuntimeError) as ei:
                await c1.publish(topic, [(b"x", b"y")], partition=foreign)
            assert b2.grpc_url in str(ei.value)

            # subscribe each partition at its owner: all 40 come back
            got = {}
            for i, addr in enumerate(brokers):
                pc = MqClient(addr)
                async for _o, k, v in pc.subscribe(topic, i, start_offset=0):
                    got[k] = v
            assert len(got) == 40

            # ---- failover: b2 dies; its partitions move to b1 ----
            await b2.stop()
            b2_stopped = True
            await _wait_brokers(b1, 1)
            count, brokers = await c1.lookup(topic)
            assert set(brokers) == {b1.grpc_url}
            more = [(f"m{i}".encode(), f"w{i}".encode()) for i in range(10)]
            assert await c1.publish_routed(topic, more) == 10

            got2 = {}
            for i in range(count):
                async for _o, k, v in c1.subscribe(topic, i, start_offset=0):
                    got2[k] = v
            # survivor serves b2's flushed history AND the new messages
            assert len(got2) == 50, sorted(got2)[:5]
            for i in range(40):
                assert got2[f"k{i}".encode()] == f"v{i}".encode()
            for i in range(10):
                assert got2[f"m{i}".encode()] == f"w{i}".encode()
        finally:
            if not b2_stopped:
                await b2.stop()
            await b1.stop()
            await cluster.stop()

    run(go())


def test_mq_epoch_fence_parks_stale_flush(tmp_path):
    """A flush racing a newer owner's activation is fenced off by the
    per-partition epoch in the filer KV: the batch parks (no colliding
    append) and the partition deactivates.  Reactivation after another
    epoch intervened counts the parked records lost instead of replaying
    them over the new owner's offsets."""

    async def go():
        cluster, broker = await make(tmp_path)
        try:
            c = MqClient(broker.grpc_url)
            topic = c.topic("fenced")
            await c.configure_topic(topic, partition_count=1)
            await c.publish(topic, [(b"", b"d%d" % i) for i in range(5)])
            p = broker.topics["default/fenced"][0]
            await p.flush()  # 0..4 durable under epoch 1
            assert p.epoch[0] == 1
            await c.publish(topic, [(b"", b"x%d" % i) for i in range(3)])
            assert len(p.pending) == 3
            # another owner activates: epoch moves on under our feet
            await broker._write_fence(p, (2, b"interloper"))
            with pytest.raises(Exception):
                await p.flush()
            assert p.parked is not None and len(p.parked[1]) == 3
            assert not p.active
            # the durable log was NOT extended by the fenced batch
            blob = await broker._read_log(p)
            from seaweedfs_tpu.mq.broker import _records_decode

            assert max(o for o, *_ in _records_decode(blob)) == 4
            # reactivation: parked epoch 1 != stored epoch 2 -> records
            # are counted lost; their offsets are NOT reused (a gap, not
            # a collision — publishers already saw 5..7 acked)
            await broker._ensure_active(p)
            assert p.parked is None and p.active and p.epoch[0] == 3
            assert p.next_offset == 8
            # a tail subscriber crossing the lost-records gap skips it
            # (no hot re-read loop) and sees the next live message
            await c.publish(topic, [(b"", b"after-gap")])
            got = []

            async def tail_reader():
                async for _o, _k, v in c.subscribe(
                    topic, 0, start_offset=0, tail=True
                ):
                    got.append(v)
                    if v == b"after-gap":
                        return

            await asyncio.wait_for(tail_reader(), 10)
            assert got == [b"d%d" % i for i in range(5)] + [b"after-gap"]
        finally:
            await broker.stop()
            await cluster.stop()

    run(go())


def test_mq_parked_batch_replays_on_reactivation(tmp_path):
    """A handoff flush that fails transiently parks the acked batch; when
    the broker reactivates the partition and no other epoch intervened,
    the parked batch replays into the log — no acked record lost."""

    async def go():
        cluster, broker = await make(tmp_path)
        try:
            c = MqClient(broker.grpc_url)
            topic = c.topic("parked")
            await c.configure_topic(topic, partition_count=1)
            await c.publish(topic, [(b"", b"d%d" % i) for i in range(5)])
            p = broker.topics["default/parked"][0]
            await p.flush()
            await c.publish(topic, [(b"", b"x%d" % i) for i in range(3)])

            real_append = broker._append_log

            async def failing_append(part, blob, epoch=None):
                raise RuntimeError("filer briefly unreachable")

            broker._append_log = failing_append
            await broker._deactivate(p)
            broker._append_log = real_append
            assert p.parked is not None and len(p.parked[1]) == 3
            assert not p.active

            # reactivate: same epoch still stored, log ends where the
            # parked batch begins -> replay
            await broker._ensure_active(p)
            assert p.parked is None and p.active
            assert p.next_offset == 8

            got = []
            async for _o, _k, v in c.subscribe(topic, 0, start_offset=0):
                got.append(v)
            assert got == [b"d0", b"d1", b"d2", b"d3", b"d4",
                           b"x0", b"x1", b"x2"]
        finally:
            await broker.stop()
            await cluster.stop()

    run(go())
