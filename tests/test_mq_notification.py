"""Notification over the in-repo MQ broker, e2e: filer meta events publish
over the wire to mq/broker.py (MqNotifier), and `filer.replicate -mqBroker`
consumes them into a second cluster — including a broker restart
mid-stream (events buffered by the notifier, consumer resumes from its
committed group offset).

Reference shape: weed/notification/kafka/kafka_queue.go publishers +
weed/command/filer_replication.go consumers.
"""
import argparse
import asyncio

import aiohttp
import pytest

from seaweedfs_tpu.command import COMMANDS
from seaweedfs_tpu.mq import MessageQueueBroker
from seaweedfs_tpu.replication.notification import MqNotifier
from seaweedfs_tpu.server.cluster import LocalCluster


def run(coro):
    return asyncio.run(coro)


async def start_pair(tmp_path):
    src = LocalCluster(
        base_dir=str(tmp_path / "src"), n_volume_servers=1, with_filer=True
    )
    await src.start()
    broker = MessageQueueBroker(
        filer_address=src.filer.url,
        filer_grpc_address=f"{src.filer.ip}:{src.filer.grpc_port}",
        port=0,
    )
    await broker.start()
    notifier = MqNotifier(broker.grpc_url, partition_count=2)
    src.filer.filer.meta_log.notifier = notifier
    dst = LocalCluster(
        base_dir=str(tmp_path / "dst"), n_volume_servers=1, with_filer=True
    )
    await dst.start()
    return src, broker, notifier, dst


def replicate_args(broker, src, dst, follow=False):
    mod = COMMANDS["filer.replicate"]
    p = argparse.ArgumentParser()
    mod.add_args(p)
    argv = [
        # explicit host:port.grpc form — a broker has no HTTP port for the
        # +10000 convention to hang off
        "-mqBroker", f"{broker.ip}:{broker.port}.{broker.port}",
        "-sourceFiler", f"{src.filer.ip}:{src.filer.port}.{src.filer.grpc_port}",
        "-targetFiler", f"{dst.filer.ip}:{dst.filer.port}.{dst.filer.grpc_port}",
    ]
    if follow:
        argv.append("-follow")
    return mod, p.parse_args(argv)


async def put(cluster, path, data):
    async with aiohttp.ClientSession() as s:
        async with s.put(
            f"http://{cluster.filer.url}{path}", data=data
        ) as r:
            assert r.status < 300, r.status


async def get(cluster, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://{cluster.filer.url}{path}") as r:
            if r.status == 404:
                return None
            assert r.status < 300, r.status
            return await r.read()


async def wait_for(cluster, path, data, timeout=15.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        got = await get(cluster, path)
        if got == data:
            return
        await asyncio.sleep(0.2)
    # dump every live task's stack before failing: a silent stall in the
    # notifier/replicator pipeline is invisible in the assertion alone
    import traceback

    for t in asyncio.all_tasks():
        frames = t.get_stack(limit=6)
        print(f"--- task {t.get_name()} ({t._coro}):")
        for f in frames:
            traceback.print_stack(f, limit=1)
    raise AssertionError(f"{path} never reached the target")


async def drain_notifier(notifier, timeout=10.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if not notifier._buf:
            return
        await asyncio.sleep(0.1)
    raise AssertionError("notifier buffer never drained to the broker")


def test_mq_notification_replicates(tmp_path):
    """Meta events flow filer -> broker (over gRPC) -> filer.replicate ->
    second cluster; catch-up mode drains and exits."""

    async def go():
        src, broker, notifier, dst = await start_pair(tmp_path)
        try:
            bodies = {
                f"/docs/f{i}.bin": (b"%d-" % i) * 200 for i in range(3)
            }
            for path, data in bodies.items():
                await put(src, path, data)
            await drain_notifier(notifier)
            mod, args = replicate_args(broker, src, dst)
            await mod.run(args)
            for path, data in bodies.items():
                assert await get(dst, path) == data
            # deletes propagate too
            async with aiohttp.ClientSession() as s:
                async with s.delete(
                    f"http://{src.filer.url}/docs/f0.bin"
                ) as r:
                    assert r.status < 300
            await drain_notifier(notifier)
            mod, args = replicate_args(broker, src, dst)
            await mod.run(args)
            assert await get(dst, "/docs/f0.bin") is None
            assert await get(dst, "/docs/f1.bin") == bodies["/docs/f1.bin"]
        finally:
            await notifier.close()
            await broker.stop()
            await src.stop()
            await dst.stop()

    run(go())


def test_mq_notification_broker_restart_mid_stream(tmp_path):
    """Kill the broker between events: the notifier buffers and retries,
    the tailing replicator reconnects and resumes from its committed
    offset, and every event still lands exactly once."""

    async def go():
        src, broker, notifier, dst = await start_pair(tmp_path)
        task = None
        try:
            await put(src, "/a.bin", b"alpha" * 100)
            await drain_notifier(notifier)
            mod, args = replicate_args(broker, src, dst, follow=True)
            task = asyncio.ensure_future(mod.run(args))
            await wait_for(dst, "/a.bin", b"alpha" * 100)

            port = broker.port
            await broker.stop()
            # events during the outage buffer in the notifier.  Poll:
            # the event is transiently OUT of the deque while an
            # in-flight publish attempt runs; the failure handler puts
            # it back within the publish timeout.
            await put(src, "/b.bin", b"bravo" * 100)
            deadline = asyncio.get_event_loop().time() + 15
            while (
                not notifier._buf
                and asyncio.get_event_loop().time() < deadline
            ):
                await asyncio.sleep(0.1)
            assert notifier._buf, "event should be buffered while broker is down"

            broker2 = MessageQueueBroker(
                filer_address=src.filer.url,
                filer_grpc_address=f"{src.filer.ip}:{src.filer.grpc_port}",
                port=port,
            )
            await broker2.start()
            try:
                # budget >= 8 notifier retry cycles (5s max backoff each):
                # under the README's load protocol a restart can eat
                # several cycles of reconnect + re-publish before landing
                await wait_for(dst, "/b.bin", b"bravo" * 100, timeout=45.0)
                # a.bin must not have been re-applied destructively
                assert await get(dst, "/a.bin") == b"alpha" * 100
            finally:
                await broker2.stop()
        finally:
            if task is not None:
                task.cancel()
                try:
                    await task
                # graftlint: allow(no-silent-swallow): best-effort
                # `await task` drain of the cancelled notifier task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            await notifier.close()
            await src.stop()
            await dst.stop()

    run(go())


def test_mq_notification_broker_failover(tmp_path):
    """TWO brokers behind the registry balancer: kill the one owning some
    partitions mid-stream; the notifier rotates bootstraps + publish_routed
    follows the new assignment, and the tailing replicator re-looks-up
    partition owners — every event still lands."""

    async def go():
        from seaweedfs_tpu.mq import MessageQueueBroker as Broker

        src = LocalCluster(
            base_dir=str(tmp_path / "src"), n_volume_servers=1,
            with_filer=True, pulse_seconds=1,
        )
        await src.start()
        masters = [src.master.advertise_url]

        def mk():
            return Broker(
                filer_address=src.filer.url,
                filer_grpc_address=f"{src.filer.ip}:{src.filer.grpc_port}",
                port=0,
                masters=masters,
            )

        b1, b2 = mk(), mk()
        await b1.start()
        await b2.start()
        for b in (b1, b2):
            deadline = asyncio.get_event_loop().time() + 8
            while asyncio.get_event_loop().time() < deadline:
                await b.balancer.refresh()
                if len(b.balancer._brokers) == 2:
                    break
                await asyncio.sleep(0.2)
            assert len(b.balancer._brokers) == 2

        notifier = MqNotifier(
            f"{b1.grpc_url},{b2.grpc_url}", partition_count=4
        )
        src.filer.filer.meta_log.notifier = notifier
        dst = LocalCluster(
            base_dir=str(tmp_path / "dst"), n_volume_servers=1,
            with_filer=True,
        )
        await dst.start()
        task = None
        b2_stopped = False
        try:
            # enough files to hash across several partitions
            for i in range(6):
                await put(src, f"/m/f{i}.bin", (b"%d!" % i) * 50)
            await drain_notifier(notifier)
            mod, args = replicate_args(b1, src, dst, follow=True)
            task = asyncio.ensure_future(mod.run(args))
            for i in range(6):
                await wait_for(dst, f"/m/f{i}.bin", (b"%d!" % i) * 50)

            await b2.stop()
            b2_stopped = True
            # write during/after the failover window; the registry drops
            # b2 within the balancer TTL and b1 takes its partitions
            for i in range(6, 12):
                await put(src, f"/m/f{i}.bin", (b"%d!" % i) * 50)
            try:
                for i in range(6, 12):
                    await wait_for(
                        dst, f"/m/f{i}.bin", (b"%d!" % i) * 50, timeout=45.0
                    )
            except AssertionError:
                import zlib

                print(
                    f"notifier: buf={len(notifier._buf)} "
                    f"draining={notifier._draining} "
                    f"dropped={notifier.dropped} "
                    f"addr={notifier._addrs[notifier._addr_idx]}"
                )
                for i in range(6, 12):
                    k = f"/m/f{i}.bin".encode()
                    print(f"f{i} -> partition {zlib.crc32(k) % 4}")
                for tkey, parts in b1.topics.items():
                    for p in parts:
                        blob = await b1._read_log(p)
                        fence = await b1._read_fence(p)
                        from seaweedfs_tpu.mq.broker import _records_decode

                        durable = [o for o, *_ in _records_decode(blob)]
                        keys = sorted(
                            {
                                k.decode(errors="replace")
                                for _, k, _, _ in p.mem
                                if k.startswith(b"/m/")
                            }
                            | {
                                k.decode(errors="replace")
                                for _, k, _, _ in _records_decode(blob)
                                if k.startswith(b"/m/")
                            }
                        )
                        print(
                            f"b1 {tkey}/{p.idx}: active={p.active} "
                            f"epoch={p.epoch[0]} next={p.next_offset} "
                            f"flushed={p.flushed_upto} "
                            f"mem_base={p.mem_base} mem={len(p.mem)} "
                            f"pending={len(p.pending)} "
                            f"parked={p.parked is not None} "
                            f"durable={len(durable)} fence={fence[0]} "
                            f"mem_keys={keys[-8:]}"
                        )
                raise
        finally:
            if task is not None:
                task.cancel()
                try:
                    await task
                # graftlint: allow(no-silent-swallow): best-effort
                # `await task` drain of the cancelled notifier task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            await notifier.close()
            if not b2_stopped:
                await b2.stop()
            await b1.stop()
            await src.stop()
            await dst.stop()

    run(go())
