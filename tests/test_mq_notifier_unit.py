"""MqNotifier unit semantics (no broker): buffering, batch atomicity
under concurrent publishes, overflow accounting, bootstrap rotation, and
close()'s final flush — the guarantees the e2e tests rely on, pinned at
the unit level where the failure injection is exact.
"""
import asyncio

import pytest

from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.replication.notification import MqNotifier


class FakeClient:
    """Stands in for MqClient: scripted failures, records publishes."""

    def __init__(self):
        self.published = []
        self.fail_next = 0
        self.configured = 0
        self.resets = 0
        self.gate = asyncio.Event()
        self.gate.set()

    @staticmethod
    def topic(name, namespace="default"):
        from seaweedfs_tpu.mq.client import MqClient

        return MqClient.topic(name, namespace)

    async def configure_topic(self, topic, partition_count=4):
        self.configured += 1
        return partition_count

    async def publish_routed(self, topic, batch):
        await self.gate.wait()
        if self.fail_next > 0:
            self.fail_next -= 1
            raise ConnectionError("broker down")
        self.published.extend(batch)
        return len(batch)

    def reset(self):
        self.resets += 1


def note(i: int) -> filer_pb2.EventNotification:
    n = filer_pb2.EventNotification()
    n.new_entry.name = f"f{i}"
    return n


def make(fake, **kw):
    n = MqNotifier("b1:1", **kw)
    n.client = fake
    return n


def test_publish_drains_in_order():
    async def go():
        fake = FakeClient()
        n = make(fake)
        for i in range(5):
            await n.publish(f"/d/f{i}", note(i))
        await n.close()
        assert [k for k, _ in fake.published] == [
            f"/d/f{i}".encode() for i in range(5)
        ]
        assert fake.configured == 1

    asyncio.run(go())


def test_retry_keeps_events_and_order():
    async def go():
        fake = FakeClient()
        fake.fail_next = 3
        n = make(fake)
        for i in range(4):
            await n.publish(f"/k{i}", note(i))
        deadline = asyncio.get_event_loop().time() + 15
        while fake.fail_next and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.1)
        await n.close()
        assert [k for k, _ in fake.published] == [
            f"/k{i}".encode() for i in range(4)
        ], "failed batches must re-queue at the FRONT, order intact"

    asyncio.run(go())


def test_concurrent_overflow_cannot_eat_inflight_batch():
    """While a batch is in-flight (awaiting the broker), overflow pops on
    the live deque must not discard events belonging to the batch — the
    batch is taken OUT of the deque before the await."""

    async def go():
        fake = FakeClient()
        n = make(fake, max_buffer=4)
        fake.gate.clear()  # hold the first publish in-flight
        for i in range(3):
            await n.publish(f"/a{i}", note(i))
        await asyncio.sleep(0.05)  # drain task now awaits inside the gate
        # overflow the buffer while the first batch is in flight
        for i in range(3, 10):
            await n.publish(f"/a{i}", note(i))
        assert n.dropped > 0
        fake.gate.set()
        await n.close()
        keys = [k for k, _ in fake.published]
        # the in-flight batch (a0..a2) must be delivered exactly once
        for i in range(3):
            assert keys.count(f"/a{i}".encode()) == 1
        # and the newest events survive the overflow
        assert f"/a9".encode() in keys

    asyncio.run(go())


def test_bootstrap_rotation_on_failure():
    async def go():
        n = MqNotifier("b1:1,b2:2", max_buffer=10)
        fake = FakeClient()
        fake.fail_next = 1
        n.client = fake
        await n.publish("/x", note(0))
        deadline = asyncio.get_event_loop().time() + 10
        while n.client is fake and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)
        # rotated to the second bootstrap after the failure
        assert n.client is not fake
        assert n.client.broker == "b2:2"
        n._closing = True
        if n._task:
            n._task.cancel()
            try:
                await n._task
            except asyncio.CancelledError:
                pass

    asyncio.run(go())
