"""Real multi-process cluster: master + volume server + filer launched as
separate `python -m seaweedfs_tpu ...` OS processes (the deployment
story, not LocalCluster), then driven end-to-end: upload through the
filer, admin shell over gRPC, S3 gateway, graceful teardown.
"""
import asyncio
import io
import os
import signal
import socket
import sys

import aiohttp
import pytest

from seaweedfs_tpu.shell import CommandEnv, run_command

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


async def spawn(*argv):
    return await asyncio.create_subprocess_exec(
        sys.executable, "-m", "seaweedfs_tpu", *argv,
        cwd=REPO,
        stdout=asyncio.subprocess.DEVNULL,
        stderr=asyncio.subprocess.PIPE,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "SWFS_NO_NATIVE_BUILD": "1"},
    )


async def wait_http(url, timeout=90.0):
    # generous default: a 1-core CI box imports jax serially in each
    # subprocess and can take >30s to bind the first port
    deadline = asyncio.get_event_loop().time() + timeout
    async with aiohttp.ClientSession() as s:
        while asyncio.get_event_loop().time() < deadline:
            try:
                async with s.get(url):
                    return
            except aiohttp.ClientError:
                await asyncio.sleep(0.25)
    raise TimeoutError(url)


def test_multiprocess_cluster(tmp_path):
    async def go():
        mp, mg, vp, vg, fp, fg = free_ports(6)
        os.makedirs(tmp_path / "meta")
        os.makedirs(tmp_path / "vol")
        procs = []
        try:
            procs.append(
                await spawn(
                    "master", "-port", str(mp), "-port.grpc", str(mg),
                    "-mdir", str(tmp_path / "meta"),
                    "-volumeSizeLimitMB", "64",
                    # the telemetry plane's staleness window is derived
                    # from the master's OWN pulse flag — match the
                    # volume server's 1s pulse or stale_after is 10s
                    "-pulseSeconds", "1",
                )
            )
            await wait_http(f"http://127.0.0.1:{mp}/cluster/status")
            master = f"127.0.0.1:{mp}.{mg}"
            procs.append(
                await spawn(
                    "volume", "-port", str(vp), "-port.grpc", str(vg),
                    "-dir", str(tmp_path / "vol"), "-mserver", master,
                    "-pulseSeconds", "1",
                )
            )
            procs.append(
                await spawn(
                    "filer", "-port", str(fp), "-port.grpc", str(fg),
                    "-master", master,
                    "-store", "sqlite", "-db", str(tmp_path / "filer.db"),
                )
            )
            await wait_http(f"http://127.0.0.1:{fp}/?limit=1")

            # data plane: upload + range read through the filer process
            data = os.urandom(512 * 1024)
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    f"http://127.0.0.1:{fp}/docs/blob.bin", data=data
                ) as r:
                    assert r.status in (200, 201), await r.text()
                async with s.get(
                    f"http://127.0.0.1:{fp}/docs/blob.bin"
                ) as r:
                    assert await r.read() == data
                async with s.get(
                    f"http://127.0.0.1:{fp}/docs/blob.bin",
                    headers={"Range": "bytes=1000-1999"},
                ) as r:
                    assert await r.read() == data[1000:2000]

            # admin shell against the real processes
            env = CommandEnv([master], out=io.StringIO())
            await env.acquire_lock()
            await run_command(env, "volume.list")
            assert "total" in env.out.getvalue()
            env.out = io.StringIO()
            await run_command(env, "cluster.ps")
            assert "filers:" in env.out.getvalue()
            env.out = io.StringIO()
            await run_command(env, "fs.ls /docs")
            assert "blob.bin" in env.out.getvalue()
            await env.release_lock()

            # CLI tools against the processes
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "seaweedfs_tpu", "upload",
                "-master", master, __file__,
                cwd=REPO, stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
                env={**os.environ, "JAX_PLATFORMS": "cpu",
                     "SWFS_NO_NATIVE_BUILD": "1"},
            )
            out, err = await asyncio.wait_for(proc.communicate(), 60)
            assert proc.returncode == 0, err.decode()
            assert b'"fid"' in out

            # telemetry round-trip: the volume process's heartbeat
            # payload surfaces in the master process's health plane
            vs_url = f"127.0.0.1:{vp}"
            async with aiohttp.ClientSession() as s:

                async def health():
                    async with s.get(
                        f"http://127.0.0.1:{mp}/cluster/health.json"
                    ) as r:
                        assert r.status == 200
                        return await r.json()

                deadline = asyncio.get_event_loop().time() + 15
                doc = await health()
                while asyncio.get_event_loop().time() < deadline:
                    node = doc["nodes"].get(vs_url)
                    if node and node["telemetry"] and not node["stale"]:
                        break
                    await asyncio.sleep(0.25)
                    doc = await health()
                node = doc["nodes"][vs_url]
                assert node["telemetry"] and not node["stale"], node
                assert "dispatcher" in node and "device" in node

                # node goes silent (SIGKILL: no goodbye): flagged stale
                # within 2 pulse intervals (pulse=1s -> 2s)
                procs[1].kill()
                assert doc["stale_after_seconds"] == 2.0
                deadline = asyncio.get_event_loop().time() + 15
                while asyncio.get_event_loop().time() < deadline:
                    doc = await health()
                    if doc["nodes"][vs_url]["stale"]:
                        break
                    await asyncio.sleep(0.5)
                assert doc["nodes"][vs_url]["stale"], doc["nodes"]
        finally:
            for p in procs:
                if p.returncode is None:
                    p.send_signal(signal.SIGINT)
            for p in procs:
                try:
                    await asyncio.wait_for(p.wait(), 10)
                except asyncio.TimeoutError:
                    p.kill()
                    await p.wait()

    asyncio.run(go())
