"""C++ native kernel tests (GFNI/AVX2 GF(256) + CRC32C), mirroring the role
of the reference's reedsolomon/crc dependencies. Skipped when the .so isn't
built (make -C seaweedfs_tpu/native)."""
import numpy as np
import pytest

from seaweedfs_tpu.ops import crc, gf256, rs_cpu

needs_native = pytest.mark.skipif(
    not rs_cpu.native_available(), reason="native lib not built"
)


@needs_native
def test_native_matches_numpy_parity():
    m = gf256.parity_matrix(10, 14)
    x = np.random.default_rng(0).integers(0, 256, (10, 99991), dtype=np.uint8)
    assert np.array_equal(
        rs_cpu.apply_matrix_native(m, x), rs_cpu.apply_matrix_numpy(m, x)
    )


@needs_native
def test_native_arbitrary_rows_and_tails():
    """Odd B exercises the scalar tail; 1..14 rows exercise row grouping."""
    rng = np.random.default_rng(1)
    for rows in (1, 2, 3, 4, 5, 9, 14):
        for b in (1, 63, 64, 65, 1000):
            m = rng.integers(0, 256, (rows, 10)).astype(np.uint8)
            x = rng.integers(0, 256, (10, b)).astype(np.uint8)
            assert np.array_equal(
                rs_cpu.apply_matrix_native(m, x),
                rs_cpu.apply_matrix_numpy(m, x),
            ), (rows, b)


@needs_native
def test_native_roundtrip_via_codec():
    from seaweedfs_tpu.ops.rs import RSCodec

    codec = RSCodec(backend="native")
    data = np.random.default_rng(2).integers(0, 256, (10, 4096), dtype=np.uint8)
    shards = codec.encode_all(data)
    present = {i: shards[i] for i in range(14) if i not in (0, 1, 12, 13)}
    rec = codec.reconstruct(present)
    for l in (0, 1, 12, 13):
        assert np.array_equal(rec[l], shards[l])


def test_crc32c_known_vector():
    # RFC 3720 test vector
    assert crc.crc32c(b"123456789") == 0xE3069283
    assert crc.crc32c(b"") == 0


def test_crc32c_chaining():
    data = b"the quick brown fox jumps over the lazy dog" * 37
    whole = crc.crc32c(data)
    assert crc.crc32c(data[10:], crc.crc32c(data[:10])) == whole


def test_crc32c_native_matches_fallback(monkeypatch):
    data = np.random.default_rng(3).integers(0, 256, 10000, dtype=np.uint8)
    hard = crc.crc32c(data)
    monkeypatch.setattr(crc, "_load_native", lambda: False)
    soft = crc.crc32c(data)
    assert hard == soft
