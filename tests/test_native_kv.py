"""Native embedded KV (native/kvstore.cpp via storage/kvstore.py) — the
leveldb-role component: bitcask log + hash index, crash replay, torn-tail
recovery, compaction; plus the NativeKvStore filer adapter's durability."""
import os

import pytest

from seaweedfs_tpu.storage.kvstore import NativeKv, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library not built"
)


def test_kv_basic_ops(tmp_path):
    kv = NativeKv(str(tmp_path / "t.kv"))
    kv.put(b"alpha", b"1" * 10)
    kv.put(b"beta", b"2" * 5000)  # exceeds the first get buffer
    kv.put(b"alpha", b"updated")
    assert kv.get(b"alpha") == b"updated"
    assert kv.get(b"beta") == b"2" * 5000
    assert kv.get(b"missing") is None
    assert len(kv) == 2
    assert kv.delete(b"beta")
    assert not kv.delete(b"beta")  # double delete reports absent
    assert kv.get(b"beta") is None
    assert len(kv) == 1
    assert dict(kv.items()) == {b"alpha": b"updated"}
    kv.close()


def test_kv_reopen_replays_log(tmp_path):
    p = str(tmp_path / "t.kv")
    kv = NativeKv(p)
    for i in range(200):
        kv.put(f"k{i}".encode(), os.urandom(50 + i))
    kv.put(b"k7", b"second-version")
    kv.delete(b"k9")
    kv.close()
    kv2 = NativeKv(p)
    assert len(kv2) == 199
    assert kv2.get(b"k7") == b"second-version"
    assert kv2.get(b"k9") is None
    kv2.close()


def test_kv_torn_tail_recovery(tmp_path):
    p = str(tmp_path / "t.kv")
    kv = NativeKv(p)
    kv.put(b"good", b"data")
    kv.close()
    with open(p, "ab") as f:
        f.write(b"\x30\x00\x00\x00\xff")  # half a record header
    kv2 = NativeKv(p)
    assert kv2.get(b"good") == b"data"
    kv2.put(b"after", b"crash")  # appends land on a clean boundary
    kv2.close()
    kv3 = NativeKv(p)
    assert kv3.get(b"after") == b"crash" and len(kv3) == 2
    kv3.close()


def test_kv_compaction_reclaims_and_preserves(tmp_path):
    p = str(tmp_path / "t.kv")
    kv = NativeKv(p)
    for i in range(50):
        kv.put(b"hot", os.urandom(1000))  # 49 superseded versions
    kv.put(b"cold", b"keep")
    kv.delete(b"hot")
    size_before = os.path.getsize(p)
    assert kv.dead_bytes > 0
    reclaimed = kv.compact()
    assert reclaimed > 0
    assert os.path.getsize(p) < size_before
    assert kv.get(b"cold") == b"keep"
    assert kv.get(b"hot") is None
    assert len(kv) == 1
    # still writable + durable after the swap
    kv.put(b"post", b"compact")
    kv.close()
    kv2 = NativeKv(p)
    assert kv2.get(b"post") == b"compact" and kv2.get(b"cold") == b"keep"
    kv2.close()


def test_filer_native_store_durability(tmp_path):
    from seaweedfs_tpu.filer.entry import MODE_DIR, Attr, Entry
    from seaweedfs_tpu.filer.filerstore import NativeKvStore, NotFoundError

    p = str(tmp_path / "filer.kv")
    s = NativeKvStore(p)
    s.insert_entry(Entry(full_path="/docs", attr=Attr(mode=0o770 | MODE_DIR)))
    for i in range(20):
        s.insert_entry(
            Entry(full_path=f"/docs/f{i:02d}", attr=Attr(file_size=i))
        )
    s.delete_entry("/docs/f03")
    s.kv_put(b"cursor", b"42")
    s.shutdown()

    s2 = NativeKvStore(p)
    names = [e.name for e in s2.list_directory_entries("/docs")]
    assert names == sorted(f"f{i:02d}" for i in range(20) if i != 3)
    page = s2.list_directory_entries("/docs", start_file_name="f05", limit=3)
    assert [e.name for e in page] == ["f06", "f07", "f08"]
    assert s2.find_entry("/docs/f10").attr.file_size == 10
    with pytest.raises(NotFoundError):
        s2.find_entry("/docs/f03")
    assert s2.kv_get(b"cursor") == b"42"
    assert s2.compact() >= 0
    assert s2.find_entry("/docs/f10").attr.file_size == 10
    s2.shutdown()


def test_kv_tombstone_churn_does_not_fill_table(tmp_path):
    """Delete-heavy workloads leave tombstone slots in the hash index;
    growth must gate on occupancy or probing spins forever once the
    initial 1024 slots fill."""
    kv = NativeKv(str(tmp_path / "churn.kv"))
    for i in range(3000):
        k = f"churn-{i}".encode()
        kv.put(k, b"v")
        kv.delete(k)
    assert len(kv) == 0
    assert kv.get(b"absent-after-churn") is None  # must not hang
    kv.put(b"alive", b"yes")
    assert kv.get(b"alive") == b"yes"
    kv.close()
    kv2 = NativeKv(str(tmp_path / "churn.kv"))  # replay must not hang either
    assert len(kv2) == 1 and kv2.get(b"alive") == b"yes"
    kv2.close()


def test_kv_torn_value_not_zero_extended(tmp_path):
    """A record whose VALUE was half-written must be dropped at replay,
    not zero-extended into a corrupt 'live' value."""
    import struct as _s

    p = str(tmp_path / "torn.kv")
    kv = NativeKv(p)
    kv.put(b"ok", b"fine")
    kv.close()
    with open(p, "ab") as f:
        # header claims a 100-byte value but only 10 bytes follow
        f.write(_s.pack("<II", 4, 100) + b"torn" + b"x" * 10)
    kv2 = NativeKv(p)
    assert kv2.get(b"torn") is None
    assert kv2.get(b"ok") == b"fine"
    assert len(kv2) == 1
    kv2.close()
