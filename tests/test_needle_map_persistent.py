"""SQLite-backed persistent needle map (the reference's leveldb index,
needle_map_leveldb.go): CompactMap-interface parity, watermark-driven
incremental open, idempotent crash replay, vacuum swap, full Volume
lifecycle with needle_map_kind="persistent".
"""
import os
import random

import pytest

from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle_map import CompactMap
from seaweedfs_tpu.storage.needle_map_persistent import (
    NativeNeedleMap,
    SqliteNeedleMap,
)


@pytest.fixture(params=["persistent", "native"])
def map_kind(request):
    return request.param


def make_map(map_kind, db, idx, version=None):
    cls = SqliteNeedleMap if map_kind == "persistent" else NativeNeedleMap
    return cls(db, idx, version)
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.storage.vacuum import vacuum


def apply_ops(m, ops):
    for op, *a in ops:
        getattr(m, op)(*a)


def random_ops(rng, n=500):
    ops = []
    for _ in range(n):
        nid = rng.randrange(1, 60)
        if rng.random() < 0.25:
            ops.append(("delete", nid))
        else:
            ops.append(("set", nid, rng.randrange(8, 1 << 30), rng.randrange(1, 10_000)))
    return ops


def test_parity_with_compact_map(tmp_path, map_kind):
    rng = random.Random(3)
    ops = random_ops(rng)
    cm = CompactMap()
    sm = make_map(map_kind, str(tmp_path / "m.sdx"), str(tmp_path / "m.idx"))
    apply_ops(cm, ops)
    apply_ops(sm, ops)
    for nid in range(1, 60):
        assert cm.get(nid) == sm.get(nid), nid
        assert cm.has(nid) == sm.has(nid)
    assert len(cm) == len(sm)
    assert sorted(cm.items()) == sorted(sm.items())
    s1, s2 = cm.stats, sm.stats
    assert (s1.file_count, s1.deleted_count, s1.file_bytes, s1.deleted_bytes,
            s1.maximum_key) == (
        s2.file_count, s2.deleted_count, s2.file_bytes, s2.deleted_bytes,
        s2.maximum_key)


def test_incremental_open_via_watermark(tmp_path, map_kind):
    """Open replays only the .idx tail past the watermark."""
    idx = str(tmp_path / "v.idx")
    db = str(tmp_path / "v.sdx")
    with open(idx, "ab") as f:
        for nid in range(1, 101):
            f.write(idx_mod.pack_entry(nid, nid * 16, 100))
    m = make_map(map_kind, db, idx)
    assert len(m) == 100 and m.get(50) == (800, 100)
    m.close()
    # append more entries while "down", reopen -> only the tail replays
    with open(idx, "ab") as f:
        for nid in range(101, 121):
            f.write(idx_mod.pack_entry(nid, nid * 16, 200))
    m2 = make_map(map_kind, db, idx)
    assert len(m2) == 120 and m2.get(110) == (1760, 200)
    # stats correct across the incremental open
    assert m2.stats.file_count == 120
    m2.close()


def test_crash_replay_is_idempotent(tmp_path, map_kind):
    """A stale watermark (crash before flush) re-applies tail entries
    without double-counting stats."""
    idx = str(tmp_path / "v.idx")
    db = str(tmp_path / "v.sdx")
    with open(idx, "ab") as f:
        for nid in range(1, 11):
            f.write(idx_mod.pack_entry(nid, nid * 16, 100))
    m = make_map(map_kind, db, idx)
    m.flush()
    stats1 = (m.stats.file_count, m.stats.file_bytes, len(m))
    # simulate crash: reopen with watermark forced stale
    if map_kind == "persistent":
        m.conn.execute("UPDATE meta SET v = 0 WHERE k = 'watermark'")
        m.conn.commit()
        m.conn.close()
    else:
        m._meta_watermark = 0
        m._save_meta()
        m.kv.close()
    m2 = make_map(map_kind, db, idx)
    assert (m2.stats.file_count, m2.stats.file_bytes, len(m2)) == stats1
    m2.close()


def test_rebuild_when_idx_shrinks(tmp_path, map_kind):
    """Vacuum rewrote the .idx smaller than the watermark -> full rebuild."""
    idx = str(tmp_path / "v.idx")
    db = str(tmp_path / "v.sdx")
    with open(idx, "ab") as f:
        for nid in range(1, 21):
            f.write(idx_mod.pack_entry(nid, nid * 16, 100))
    make_map(map_kind, db, idx).close()
    with open(idx, "wb") as f:  # compacted: fewer entries, new offsets
        for nid in range(1, 6):
            f.write(idx_mod.pack_entry(nid, nid * 32, 77))
    m = make_map(map_kind, db, idx)
    assert len(m) == 5 and m.get(3) == (96, 77) and m.get(15) is None
    m.close()


def test_reopen_does_not_resurrect_deleted_needles(tmp_path, map_kind):
    kind = map_kind
    """Write, delete, clean close, reopen: the deleted needle must stay
    deleted and reopen must not rescan the whole .dat (stale indexed_end
    would re-apply the needle's live record from disk)."""
    vdir = str(tmp_path)
    v = Volume(vdir, 3, needle_map_kind=kind)
    v.write(1, 0xAA, b"first")
    v.write(2, 0xAA, b"second")
    v.delete(1, 0xAA)
    v.close()

    v2 = Volume(vdir, 3, needle_map_kind=kind)
    with pytest.raises(KeyError):
        v2.read(1)
    assert v2.read(2, 0xAA).data == b"second"
    assert len(v2.nm) == 1
    # indexed_end covers the last live record, so no duplicate idx entries
    # were appended by tail recovery
    import seaweedfs_tpu.storage.idx as idxm

    n_entries = os.path.getsize(v2.idx_path) // idxm.entry_size()
    assert n_entries == 3, f"recovery duplicated idx entries: {n_entries}"
    v2.close()


def test_volume_lifecycle_persistent(tmp_path, map_kind):
    kind = map_kind
    vdir = str(tmp_path)
    v = Volume(vdir, 9, needle_map_kind=kind)
    payloads = {i: os.urandom(200 + i) for i in range(1, 40)}
    for nid, data in payloads.items():
        v.write(nid, 0xCAFE, data)
    v.delete(5, 0xCAFE)
    v.delete(17, 0xCAFE)
    assert os.path.exists(v.sdx_path if kind == "persistent" else v.ndx_path)
    for nid, data in payloads.items():
        if nid in (5, 17):
            with pytest.raises(KeyError):
                v.read(nid)
        else:
            assert v.read(nid, 0xCAFE).data == data

    # vacuum reclaims the deleted records and the map survives the swap
    ratio = vacuum(v)
    assert ratio > 0
    for nid, data in payloads.items():
        if nid not in (5, 17):
            assert v.read(nid, 0xCAFE).data == data
    v.close()

    # reopen: persistent map comes back without manual idx replay
    v2 = Volume(vdir, 9, needle_map_kind=kind)
    assert type(v2.nm).__name__ == (
        "SqliteNeedleMap" if kind == "persistent" else "NativeNeedleMap"
    )
    for nid, data in payloads.items():
        if nid not in (5, 17):
            assert v2.read(nid, 0xCAFE).data == data
    assert len(v2.nm) == len(payloads) - 2
    v2.close()
