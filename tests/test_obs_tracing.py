"""End-to-end request tracing (seaweedfs_tpu/obs/): one trace id spans
the filer's inbound request, its chunk fan-out to the volume server, and
the volume server's EC serving stages (dispatcher queue hop included),
all visible in /debug/traces; the per-stage histograms ride /metrics.

The degraded cluster comes from bench.build_degraded_cluster (the one
choreography shared with the benchmark, warm_sizes=() per CI convention
so the XLA-fallback kernels compile in milliseconds at first use).
"""
import asyncio
import time
from types import SimpleNamespace

import aiohttp

from seaweedfs_tpu import obs, stats


def run(coro):
    return asyncio.run(coro)


# ----------------------------------------------------------------- units


def test_obs_config_validation():
    import pytest

    from seaweedfs_tpu.obs import ObsConfig

    assert ObsConfig().validated().trace_ring == 256
    with pytest.raises(ValueError):
        ObsConfig(trace_ring=0).validated()
    with pytest.raises(ValueError):
        ObsConfig(slow_ms=-1).validated()


def test_trace_header_roundtrip():
    assert obs.parse_trace_header("") == (None, "")
    assert obs.parse_trace_header("abc") == ("abc", "")
    assert obs.parse_trace_header("abc-def") == ("abc", "def")
    t, tok = obs.start_trace("GET /x", "volume", "srv")
    try:
        hdr = obs.outbound_headers()[obs.TRACE_HEADER]
        assert hdr == f"{t.trace_id}-{t.root_id}"
        md = dict(obs.grpc_metadata())
        assert md[obs.GRPC_TRACE_KEY] == hdr
    finally:
        obs.finish_trace(t, tok, 200)
    # outside a trace: nothing to propagate
    assert obs.outbound_headers() == {}
    assert obs.grpc_metadata() is None


def test_trace_ring_bounded_and_newest_first():
    from seaweedfs_tpu.obs.trace import Trace, TraceRing

    ring = TraceRing(capacity=3)
    for i in range(5):
        ring.add(Trace(f"id{i}", "volume", f"req{i}"))
    snap = ring.snapshot()
    assert [t["trace_id"] for t in snap] == ["id4", "id3", "id2"]
    assert ring.snapshot(limit=1)[0]["trace_id"] == "id4"


def test_span_nesting_and_stage_sink():
    # trace mode: spans nest via the contextvar
    t, tok = obs.start_trace("GET /y", "volume")
    with obs.span("shard_read", bytes=7):
        with obs.span("host_reconstruct"):
            pass
    obs.finish_trace(t, tok, 200)
    d = obs.RING.snapshot(1)[0]
    by_name = {s["name"]: s for s in d["spans"]}
    assert by_name["host_reconstruct"]["parent_span_id"] == \
        by_name["shard_read"]["span_id"]
    assert by_name["shard_read"]["annotations"]["bytes"] == 7
    # sink mode (no trace in context): durations/annotations accumulate
    with obs.stage_sink() as sink:
        for _ in range(3):
            with obs.span("device_execute", h2d_bytes=10):
                pass
    dur, calls, ann = sink["device_execute"]
    assert calls == 3 and dur > 0 and ann["h2d_bytes"] == 30


def test_slow_request_log(caplog):
    import logging

    from seaweedfs_tpu.obs import ObsConfig

    obs.configure(ObsConfig(slow_ms=0.0001))
    try:
        with caplog.at_level(logging.WARNING, logger="obs"):
            t, tok = obs.start_trace("GET /slow", "volume")
            with obs.span("shard_read"):
                time.sleep(0.002)
            obs.finish_trace(t, tok, 200)
        assert any(
            "slow request" in r.message and t.trace_id in r.message
            for r in caplog.records
        )
    finally:
        obs.configure(ObsConfig())


def test_mq_fence_conflict_counter():
    """The residual epoch-fence window is observed: an activation that
    finds the log tail moved after its resync bumps the conflict counter
    and resyncs next_offset past the interloper's records."""
    from seaweedfs_tpu.mq.broker import MessageQueueBroker, Partition

    async def go():
        broker = MessageQueueBroker(filer_address="127.0.0.1:1")
        p = Partition(broker, "default/t", 0)
        tails = iter([5, 7])  # resync sees 5; re-read sees 7 (conflict)

        async def fake_last_offset(part):
            return next(tails)

        async def fake_fence_read(part):
            return (0, b"")

        async def fake_fence_write(part, epoch):
            return None

        broker._last_offset = fake_last_offset
        broker._read_fence = fake_fence_read
        broker._write_fence = fake_fence_write
        before = stats.REGISTRY.get_sample_value(
            "SeaweedFS_mq_fence_conflict_total"
        )
        await broker._ensure_active(p)
        after = stats.REGISTRY.get_sample_value(
            "SeaweedFS_mq_fence_conflict_total"
        )
        assert after == before + 1
        assert p.next_offset == 8  # resynced over the interloper's tail
        assert p.active

    run(go())


def test_drain_lane_does_not_inherit_spawner_trace():
    """The dispatcher's drain lane is spawned from a traced request and
    asyncio copies that context into the task — the lane must be
    DETACHED, or every later request's batch spans would append to the
    spawner's finished trace.  Each request's trace must carry its own
    batch stages via the queue-hop replay, and only its own."""
    from seaweedfs_tpu.serving import EcReadDispatcher, ServingConfig

    class Store:
        def ec_volume_is_resident(self, vid):
            return True

        def read_ec_needles_batch(
            self, vid, requests, remote_read=None, zero_copy=False
        ):
            time.sleep(0.002)  # keep the lane alive across both reads
            return [b"x"] * len(requests)

    async def go():
        d = EcReadDispatcher(
            Store(), lambda vid: None,
            ServingConfig(max_batch=4, max_wait_us=500),
        )

        async def traced_read(nid):
            t, tok = obs.start_trace(f"GET /{nid}", "volume")
            await d.read(1, nid, None)
            obs.finish_trace(t, tok, 200)
            return t

        t1, t2 = await asyncio.gather(traced_read(1), traced_read(2))
        for t in (t1, t2):
            names = [s.name for s in t.spans]
            assert "queue_wait" in names, names
            assert "batch_dispatch" in names, names
        # a second round on the same (still-warm) dispatcher must not
        # grow the finished traces from round one
        n1 = len(t1.spans)
        await traced_read(3)
        assert len(t1.spans) == n1, "drain lane kept spawner's trace"

    run(go())


# ------------------------------------------------------------------- e2e


def test_trace_propagation_filer_to_volume(tmp_path):
    """One trace id spans filer -> volume -> dispatcher: a degraded EC
    read through the filer produces, in /debug/traces, a filer-role
    trace (chunk_fetch span) and a volume-role trace (queue_wait +
    device_execute + shard_read spans) under the SAME trace id, and
    /metrics exposes every stage histogram."""
    from bench import build_degraded_cluster

    async def go():
        cluster, vs, blobs, _vid = await build_degraded_cluster(
            str(tmp_path), n_blobs=6, device_cache=True,
            cache_budget=1 << 30, warm_sizes=(), with_filer=True,
        )
        try:
            fs = cluster.filer
            fid, data = next(iter(blobs.items()))
            from seaweedfs_tpu.filer import Attr, Entry
            from seaweedfs_tpu.pb import filer_pb2

            now = int(time.time())
            await fs.filer.create_entry(
                Entry(
                    full_path="/blob.bin",
                    attr=Attr(mtime=now, crtime=now, file_size=len(data)),
                    chunks=[
                        filer_pb2.FileChunk(
                            file_id=fid, offset=0, size=len(data)
                        )
                    ],
                )
            )
            async with aiohttp.ClientSession() as sess:
                async with sess.get(f"http://{fs.url}/blob.bin") as r:
                    assert r.status == 200
                    assert await r.read() == data
                    hdr = r.headers.get(obs.TRACE_HEADER, "")
                trace_id, _ = obs.parse_trace_header(hdr)
                assert trace_id, "filer response carries no trace id"

                # /debug/traces on the volume server (and the filer's
                # metrics port) serves the ring; in-process roles share
                # it like they share stats.REGISTRY
                async with sess.get(
                    f"http://{vs.url}/debug/traces"
                ) as r:
                    assert r.status == 200
                    traces = (await r.json())["traces"]
                async with sess.get(
                    f"http://{fs.ip}:{fs.metrics_port}/debug/traces"
                ) as r:
                    assert r.status == 200

                same_id = [t for t in traces if t["trace_id"] == trace_id]
                roles = {t["role"] for t in same_id}
                assert {"filer", "volume"} <= roles, (roles, same_id)

                filer_t = next(t for t in same_id if t["role"] == "filer")
                filer_spans = {s["name"] for s in filer_t["spans"]}
                assert "chunk_fetch" in filer_spans

                vol_t = next(t for t in same_id if t["role"] == "volume")
                vol_spans = {s["name"] for s in vol_t["spans"]}
                # acceptance: queue-wait, device-execute (resident
                # path), and shard-read stages on the volume trace
                assert {
                    "queue_wait", "batch_dispatch", "device_execute",
                    "shard_read",
                } <= vol_spans, vol_spans
                # device annotations made it through the queue hop
                dev = next(
                    s for s in vol_t["spans"]
                    if s["name"] == "device_execute"
                )
                ann = dev.get("annotations", {})
                assert ann.get("d2h_bytes", 0) > 0
                assert "compile_misses" in ann
                # the volume span is a child of the filer's outbound
                # span: its inbound parent id came off the header
                assert vol_t["parent_span_id"], vol_t

                # every stage histogram is scrapeable (pre-registered,
                # so even stages this read didn't exercise appear)
                async with sess.get(f"http://{vs.url}/metrics") as r:
                    text = await r.text()
                assert "SeaweedFS_request_stage_seconds_bucket" in text
                for stage in stats.TRACE_STAGES:
                    assert f'stage="{stage}"' in text, stage

                # the shell's operator view of the same ring
                from seaweedfs_tpu.shell.command_volume import (
                    cmd_volume_trace,
                )

                lines = []
                env = SimpleNamespace(write=lines.append)
                await cmd_volume_trace(env, ["-node", vs.url])
                out = "\n".join(lines)
                assert trace_id in out
                assert "device_execute" in out
        finally:
            await cluster.stop()

    run(go())
