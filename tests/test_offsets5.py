"""5-byte needle-map offsets: volumes past the 32GB 4-byte address cap.

Reference: the `5BytesOffset` build tag (types/offset_5bytes.go:14-17)
raises the cap to 8TB; here t.set_offset_size(5) is the runtime
equivalent (process-wide, like the tag).  Covers the wire encodings,
the idx walker, a REAL >32GB-addressed sparse volume round-trip, and
EC encode/.ecx/degraded-read in 17-byte-entry mode.
"""
import os

import pytest

from seaweedfs_tpu.storage import ec, idx as idx_mod
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.volume import Volume

from test_ec import encode_volume, make_volume

GB = 1024 * 1024 * 1024


@pytest.fixture
def five_bytes():
    t.set_offset_size(5)
    yield
    t.set_offset_size(4)


def test_default_mode_unchanged():
    assert t.OFFSET_SIZE == 4
    assert t.NEEDLE_MAP_ENTRY_SIZE == 16
    assert t.MAX_POSSIBLE_VOLUME_SIZE == 32 * GB


def test_offset_encoding_roundtrip(five_bytes):
    assert t.NEEDLE_MAP_ENTRY_SIZE == 17
    assert t.MAX_POSSIBLE_VOLUME_SIZE == 8 * 1024 * GB
    for off in (0, 8, 32 * GB, 33 * GB + 8, 8 * 1024 * GB - 8):
        b = t.offset_to_bytes(off)
        assert len(b) == 5
        assert t.offset_from_bytes(b) == off
    # reference byte order: low word big-endian, high byte appended
    b = t.offset_to_bytes((1 << 32) * t.NEEDLE_PADDING_SIZE)
    assert b == bytes([0, 0, 0, 0, 1])


def test_idx_pack_parse_above_32gb(five_bytes, tmp_path):
    path = str(tmp_path / "big.idx")
    entries = [
        (1, 0, 100),
        (2, 33 * GB, 4096),
        (3, 100 * GB + 8, 1 << 20),
        (4, 0, t.TOMBSTONE_FILE_SIZE),
    ]
    with open(path, "wb") as f:
        for nid, off, size in entries:
            f.write(idx_mod.pack_entry(nid, off, size))
    assert idx_mod.entry_count(path) == 4
    assert list(idx_mod.walk(path)) == entries


def test_sparse_volume_past_32gb_roundtrip(five_bytes, tmp_path):
    """Write/read needles ABOVE the 4-byte cap on a sparse .dat — the
    VERDICT 'done' condition for this feature."""
    v = Volume(str(tmp_path), 1)
    blob_a = os.urandom(5000)
    v.write(1, 0xAAAA, blob_a, name=b"low")
    # jump the append position past 32GB (sparse hole, no real disk use)
    v._dat.truncate(33 * GB)
    blob_b = os.urandom(7000)
    v.write(2, 0xBBBB, blob_b, name=b"high")
    off, _ = v.nm.get(2)
    assert off >= 33 * GB
    assert v.read(1, 0xAAAA).data == blob_a
    assert v.read(2, 0xBBBB).data == blob_b
    v.close()

    # reload from disk: the 17-byte idx replays correctly
    v2 = Volume(str(tmp_path), 1)
    assert v2.read(2, 0xBBBB).data == blob_b
    assert v2.read(1, 0xAAAA).data == blob_a
    v2.close()


def test_ec_roundtrip_in_5byte_mode(five_bytes, tmp_path):
    """ec.encode -> .ecx (17-byte entries) -> degraded read, all in
    5-byte mode."""
    v, blobs = make_volume(tmp_path)
    base = encode_volume(v)
    assert os.path.getsize(base + ".ecx") % 17 == 0
    ev = ec.EcVolume(str(tmp_path), v.id)
    down = {0, 11}
    for i in range(14):
        if i not in down:
            ev.add_shard(i)
    for nid, (cookie, data) in blobs.items():
        assert ev.read_needle(nid, cookie=cookie).data == data
    # delete path writes the tombstone at the 5-byte-mode field offset
    victim = next(iter(blobs))
    ev.delete_needle(victim)
    with pytest.raises(Exception):
        ev.read_needle(victim)
    ev.close()


def test_master_rejects_offset_width_mismatch():
    """A volume server heartbeating a different needle-map offset width
    is rejected loudly — mixed modes write mutually unreadable
    .idx/.ecx files, so the cluster must refuse to form."""
    import asyncio

    import grpc
    import pytest as _pytest

    from seaweedfs_tpu.pb import Stub, master_pb2
    from seaweedfs_tpu.pb.rpc import channel
    from seaweedfs_tpu.server.master import MasterServer

    async def go():
        m = MasterServer(port=0)
        await m.start()
        try:
            stub = Stub(channel(m.grpc_url), master_pb2, "Seaweed")

            async def feed():
                yield master_pb2.Heartbeat(
                    ip="127.0.0.1", port=9, offset_bytes=5
                )

            with _pytest.raises(grpc.aio.AioRpcError) as ei:
                async for _ in stub.SendHeartbeat(feed()):
                    pass
            assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
            assert "offset width mismatch" in ei.value.details()
        finally:
            await m.stop()

    asyncio.run(go())
