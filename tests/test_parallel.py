"""Mesh-sharded EC math vs the numpy oracle, on the virtual 8-device mesh
(the in-process multi-node test shape of reference topology_test.go)."""
import os

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256, rs, rs_cpu
from seaweedfs_tpu.parallel import distributed_apply_matrix, make_mesh


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    return rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (5, 1), (2, 1)])
def test_distributed_encode_matches_oracle(data, mesh_shape):
    import jax

    n_shard, n_batch = mesh_shape
    if n_shard * n_batch > len(jax.devices()):
        pytest.skip("not enough devices")
    mesh = make_mesh(n_shard, n_batch)
    parity_m = rs.RSCodec().matrix[10:]
    want = rs_cpu.apply_matrix_numpy(parity_m, data)
    got = np.asarray(distributed_apply_matrix(mesh, parity_m, data))
    np.testing.assert_array_equal(got, want)


def test_distributed_reconstruct_matches_oracle(data):
    """Pod-scale rebuild: survivors sharded over the mesh's shard axis,
    one psum reconstructs the missing shards."""
    codec = rs.RSCodec()
    full = codec.encode_all(data)
    missing = [0, 3, 11, 13]
    present = [i for i in range(14) if i not in missing]
    rmat, use = gf256.reconstruction_matrix(10, 14, present, missing)
    survivors = full[use]  # [10, B] in `use` order
    mesh = make_mesh(2, 4)
    got = np.asarray(distributed_apply_matrix(mesh, rmat, survivors))
    np.testing.assert_array_equal(got, full[missing])


def test_distributed_full_cycle_with_delete(data):
    """Encode on one mesh layout, reconstruct on another: the math is
    layout-independent."""
    codec = rs.RSCodec()
    full = codec.encode_all(data)
    parity_m = codec.matrix[10:]
    mesh_a = make_mesh(5, 1)
    parity = np.asarray(distributed_apply_matrix(mesh_a, parity_m, data))
    np.testing.assert_array_equal(parity, full[10:])


def test_distributed_blockdiag_and_degraded_read(data):
    """Block-diagonal bulk encode + batched degraded read under shard_map
    (the pod-scale forms of the single-chip fast paths)."""
    import jax

    from seaweedfs_tpu.parallel import (
        distributed_degraded_read,
        distributed_encode_blockdiag,
    )

    mesh = make_mesh(2, 2, devices=jax.devices("cpu")[:4])
    parity_m = rs.RSCodec().matrix[10:]
    b = data.shape[1] - data.shape[1] % (4 * 2 * 128)
    data = data[:, :b]
    want = rs_cpu.apply_matrix_numpy(parity_m, data)
    got = np.asarray(distributed_encode_blockdiag(mesh, parity_m, data))
    np.testing.assert_array_equal(got, want)

    codec = rs.RSCodec(backend="numpy")
    full = codec.encode_all(data)
    present = [i for i in range(14) if i not in (3, 11)]
    reqs = [(5, 1000), (b - 700, 700), (1300, 2048)]
    pieces = distributed_degraded_read(
        mesh, full[present][:10], present[:10], 3, reqs
    )
    for (off, size), piece in zip(reqs, pieces):
        assert piece == full[3][off : off + size].tobytes()


def test_two_process_host_staging(tmp_path):
    """BASELINE config 5's staging story: TWO separate processes, each
    contributing only its process-local input slice via
    jax.make_array_from_process_local_data, jointly running the sharded
    encode over one logical 8-device mesh with the psum crossing process
    boundaries.  Each worker asserts the full result against the oracle."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-m", "seaweedfs_tpu.parallel.distributed",
                "--staged-worker",
                "--coordinator", f"127.0.0.1:{port}",
                "--nproc", "2", "--pid", str(pid),
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out.decode(errors="replace"))
        except subprocess.TimeoutExpired:
            # a hung worker (e.g. peer crashed before initialize): collect
            # whatever output every remaining worker produced and FALL
            # THROUGH to the assertions so the failure message shows the
            # root cause, not a bare timeout
            for p in procs[len(outs):]:
                p.kill()
                out, _ = p.communicate()
                outs.append(
                    "[killed after timeout]\n" + out.decode(errors="replace")
                )
    finally:
        # no exception path may leak workers (KeyboardInterrupt, pytest
        # timeout, decode errors): kill anything still running
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"staged worker {pid}: ok" in out, out
