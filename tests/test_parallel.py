"""Mesh-sharded EC math vs the numpy oracle, on the virtual 8-device mesh
(the in-process multi-node test shape of reference topology_test.go)."""
import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256, rs, rs_cpu
from seaweedfs_tpu.parallel import distributed_apply_matrix, make_mesh


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    return rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (5, 1), (2, 1)])
def test_distributed_encode_matches_oracle(data, mesh_shape):
    import jax

    n_shard, n_batch = mesh_shape
    if n_shard * n_batch > len(jax.devices()):
        pytest.skip("not enough devices")
    mesh = make_mesh(n_shard, n_batch)
    parity_m = rs.RSCodec().matrix[10:]
    want = rs_cpu.apply_matrix_numpy(parity_m, data)
    got = np.asarray(distributed_apply_matrix(mesh, parity_m, data))
    np.testing.assert_array_equal(got, want)


def test_distributed_reconstruct_matches_oracle(data):
    """Pod-scale rebuild: survivors sharded over the mesh's shard axis,
    one psum reconstructs the missing shards."""
    codec = rs.RSCodec()
    full = codec.encode_all(data)
    missing = [0, 3, 11, 13]
    present = [i for i in range(14) if i not in missing]
    rmat, use = gf256.reconstruction_matrix(10, 14, present, missing)
    survivors = full[use]  # [10, B] in `use` order
    mesh = make_mesh(2, 4)
    got = np.asarray(distributed_apply_matrix(mesh, rmat, survivors))
    np.testing.assert_array_equal(got, full[missing])


def test_distributed_full_cycle_with_delete(data):
    """Encode on one mesh layout, reconstruct on another: the math is
    layout-independent."""
    codec = rs.RSCodec()
    full = codec.encode_all(data)
    parity_m = codec.matrix[10:]
    mesh_a = make_mesh(5, 1)
    parity = np.asarray(distributed_apply_matrix(mesh_a, parity_m, data))
    np.testing.assert_array_equal(parity, full[10:])
