"""Descriptor-driven gRPC infra: unary + bidi streaming over a real
in-process grpc.aio server."""
import asyncio

import grpc
import pytest

from seaweedfs_tpu.pb import Stub, generic_handler, server_address
from seaweedfs_tpu.pb import master_pb2


class FakeMaster:
    async def Assign(self, request, context):
        return master_pb2.AssignResponse(
            fid=f"1,00000064{0xDEAD:08x}", count=request.count or 1
        )

    async def SendHeartbeat(self, request_iterator, context):
        async for hb in request_iterator:
            yield master_pb2.HeartbeatResponse(
                volume_size_limit=1000, leader=hb.ip
            )


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def test_server_address():
    assert server_address.parse("localhost:9333") == ("localhost", 9333, 19333)
    assert server_address.parse("h:8080.18081") == ("h", 8080, 18081)
    assert server_address.grpc_address("h:9333") == "h:19333"


def test_unary_and_streaming(loop):
    async def run():
        server = grpc.aio.server()
        server.add_generic_rpc_handlers(
            [generic_handler(master_pb2, "Seaweed", FakeMaster())]
        )
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()
        try:
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                stub = Stub(ch, master_pb2, "Seaweed")
                resp = await stub.Assign(master_pb2.AssignRequest(count=3))
                assert resp.count == 3 and resp.fid.startswith("1,")

                async def pulses():
                    for ip in ("a", "b"):
                        yield master_pb2.Heartbeat(ip=ip)

                got = []
                async for r in stub.SendHeartbeat(pulses()):
                    got.append(r.leader)
                assert got == ["a", "b"]

                # unimplemented method -> UNIMPLEMENTED, not a crash
                with pytest.raises(grpc.aio.AioRpcError) as ei:
                    await stub.LookupVolume(master_pb2.LookupVolumeRequest())
                assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
        finally:
            await server.stop(None)

    loop.run_until_complete(run())
