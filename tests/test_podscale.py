"""True pod scale (r23): multi-process resident serving over
`jax.distributed`, at four depths:

  * degrade equality — a single-process `global_serving_mesh` resolves
    to EXACTLY the local serving mesh (same devices, same width-1
    None degrade), and a `global_mesh=True` DeviceShardCache keeps the
    full r19 surface (n_hosts=1, every lane local, byte-equal
    reconstructs against the local-mesh cache and the numpy oracle);
  * the pod program itself — `cache.multiprocess = True` forces the
    replicated-output all_gather reconstruct path (the kernel every
    host of a real pod runs) on the conftest's 8-device mesh, still
    byte-exact (the check_rep=False replication-inference regression);
  * host-aware placement — with device_host split 4|4, whole pins land
    only on THIS process's lanes while the mesh claim for big shards
    stays a pure function of size (identical on every host);
  * a real 2-process boundary — two `bench.py _podscale_worker`
    subprocesses join over `jax.distributed.initialize` on a CPU mesh
    and each byte-verifies the lanes it owns; a killed pod member then
    escalates the repair planner (pod_exposed), `_avoid_pods` spreads
    replicas off the pod, a hedge prefers spares outside the slow
    peer's pod, the master's health doc flags the degraded pod row,
    and the `-ec.mesh.*` config fast-fails bad wiring at startup.
"""
import json
import os
import socket
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace

import numpy as np
import pytest

from seaweedfs_tpu.ops import rs, rs_resident
from seaweedfs_tpu.parallel import mesh as mesh_mod
from seaweedfs_tpu.pb import master_pb2
from seaweedfs_tpu.repair import planner
from seaweedfs_tpu.serving.config import ServingConfig
from seaweedfs_tpu.stats.cluster import ClusterTelemetry
from seaweedfs_tpu.topology.volume_growth import _avoid_pods
from seaweedfs_tpu.utils import faultpolicy as fp

N_DEV = 8


@pytest.fixture(scope="module")
def encoded():
    """One 64KB-shard volume's 14 shards + the numpy oracle."""
    rng = np.random.default_rng(2023)
    data = rng.integers(0, 256, size=(10, 64 * 1024), dtype=np.uint8)
    return rs.RSCodec(backend="numpy").encode_all(data)


def _pod_cache(**kw):
    kw.setdefault("shard_quantum", 1 << 18)
    kw.setdefault("mesh_devices", 0)
    kw.setdefault("mesh_min_shard_bytes", 0)
    kw.setdefault("global_mesh", True)
    c = rs_resident.DeviceShardCache(**kw)
    c.warm_sizes = ()  # CI convention: no AOT grid compile unless asked
    return c


# ------------------------------------------------- single-process degrade


class TestGlobalMeshDegrade:
    def test_global_mesh_matches_local_single_process(self):
        g = mesh_mod.global_serving_mesh(0)
        l = mesh_mod.serving_mesh(0)
        assert g is not None and l is not None
        assert g.axis_names == l.axis_names == (mesh_mod.SHARD_AXIS,)
        assert list(g.devices.flat) == list(l.devices.flat), (
            "single-process global mesh must resolve to the exact "
            "local device order — existing deployments see no change"
        )

    def test_global_mesh_width1_degrades_to_none(self):
        # same `_serving_mesh_or_none` rule as the local constructor
        assert mesh_mod.global_serving_mesh(1) is None

    def test_global_cache_keeps_the_r19_surface(self):
        c = _pod_cache()
        assert c.n_devices == N_DEV
        assert c.n_hosts == 1
        assert c.multiprocess is False
        assert c._local_dev_indices == list(range(N_DEV))
        # mesh claims spread over the full pod width
        plan = c.plan_pin(14, 1 << 20)
        assert set(plan) == set(range(N_DEV))

    def test_global_vs_local_reconstruct_byte_equal(self, encoded):
        reqs = [(3, 0, 1000), (3, 5000, 4096), (0, 111, 3333)]
        pieces = []
        for global_mesh in (True, False):
            c = _pod_cache(global_mesh=global_mesh)
            for sid in range(14):
                if sid != 3:
                    c.put(51, sid, encoded[sid])
            assert c.placement(51) == "mesh"
            pieces.append(rs_resident.reconstruct_intervals(c, 51, reqs))
        for (sid, off, size), g_piece, l_piece in zip(
            reqs, pieces[0], pieces[1]
        ):
            oracle = encoded[sid][off : off + size].tobytes()
            assert g_piece == oracle, f"global mesh wrong at sid={sid}"
            assert l_piece == oracle, f"local mesh wrong at sid={sid}"


# --------------------------------------------------- pod program (forced)


class TestPodProgramKernel:
    def test_forced_multiprocess_reconstruct_byte_equal(self, encoded):
        """`multiprocess = True` routes staging through
        make_array_from_process_local_data and reconstructs through the
        replicated-output all_gather kernel — the program every host of
        a real pod executes in lockstep.  Single-process it must stay
        byte-exact (and this anchors the check_rep=False fix: the
        replicated out_specs can't satisfy static replication
        inference, so a regression here is an XLA error, not a silent
        wrong answer)."""
        c = _pod_cache()
        c.multiprocess = True  # pod-program emulation, one process
        for sid in range(14):
            if sid != 5:
                c.put(52, sid, encoded[sid])
        assert c.placement(52) == "mesh"
        reqs = [(5, 0, 2048), (5, 60000, 4000), (1, 7, 1009)]
        got = rs_resident.reconstruct_intervals(c, 52, reqs)
        for (sid, off, size), piece in zip(reqs, got):
            assert piece == encoded[sid][off : off + size].tobytes(), (
                f"pod program mismatch at sid={sid} off={off}"
            )


# ------------------------------------------------- host-aware placement


class TestHostAwarePlacement:
    @pytest.fixture()
    def split_hosts(self, monkeypatch):
        """Pretend the 8-device mesh spans two 4-lane hosts (devices
        0-3 ours, 4-7 the peer's).  The lru-cached mesh object is
        host-agnostic, so only DeviceShardCache.__init__'s ownership
        bookkeeping sees the split."""
        monkeypatch.setattr(
            mesh_mod, "device_host", lambda d: 0 if d.id < 4 else 1
        )

    def test_whole_pins_stay_host_local(self, split_hosts, encoded):
        c = _pod_cache(mesh_min_shard_bytes=1 << 30)  # never mesh
        assert c.n_hosts == 2 and c.multiprocess
        assert c._local_dev_indices == [0, 1, 2, 3]
        for vid in (61, 62, 63):
            for sid in range(3):
                c.put(vid, sid, encoded[sid])
        for vid in (61, 62, 63):
            place = c.placement(vid)
            assert place in (0, 1, 2, 3), (
                f"whole pin for vid {vid} landed on a peer host's "
                f"lane ({place!r}) — unaddressable in a real pod"
            )
        arr = c.get(61, 0)
        got = np.asarray(arr)[: encoded[0].size]
        assert np.array_equal(got, encoded[0])

    def test_mesh_claim_is_pure_function_of_size(self, split_hosts):
        """Big shards claim "mesh" from EVERY host — the claim must be
        a pure function of the shard size so pod members agree on the
        layout without coordination (one volume never straddles)."""
        c = _pod_cache(mesh_min_shard_bytes=1 << 20)
        big = c.plan_pin(14, 2 << 20)
        assert set(big) == set(range(N_DEV)), "mesh spread, all lanes"
        small = c.plan_pin(14, 1 << 10)
        assert set(small) <= {0, 1, 2, 3}, "small pin stays host-local"


# ---------------------------------------------- real 2-process boundary


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(n_local_devices: int) -> dict:
    env = dict(os.environ)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(
        f"--xla_force_host_platform_device_count={n_local_devices}"
    )
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_two_process_mesh_spans_hosts_and_byte_verifies():
    """Two real `bench.py _podscale_worker` processes join over
    `jax.distributed.initialize` (4 forced CPU devices each), stage the
    same seeded working set in SPMD lockstep, and each byte-verifies
    every lane it owns.  Together they must present one 8-lane pod:
    disjoint local lanes covering the full mesh, zero mismatches."""
    bench = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    port = _free_port()
    procs = []
    for rank in range(2):
        cfg = {
            "process_id": rank,
            "process_count": 2,
            "coordinator": f"127.0.0.1:{port}",
            "n_volumes": 2,
            "shard_kb": 16,
            "seed": 20260808,
            "hold": False,
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, bench, "_podscale_worker", json.dumps(cfg)],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                env=_worker_env(4),
                cwd=os.path.dirname(bench),
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    by_rank = {o["rank"]: o for o in outs}
    assert set(by_rank) == {0, 1}
    for o in outs:
        assert o["n_devices"] == N_DEV, "each member sees the POD mesh"
        assert o["n_hosts"] == 2 and o["multiprocess"]
        assert o["all_mesh_placed"]
        assert o["lanes_checked"] > 0
        assert o["lane_mismatches"] == 0, "cross-host lane bytes wrong"
    lanes0 = set(by_rank[0]["local_lanes"])
    lanes1 = set(by_rank[1]["local_lanes"])
    assert lanes0 | lanes1 == set(range(N_DEV))
    assert not (lanes0 & lanes1), "hosts must own disjoint lanes"


# ------------------------------------------- killed member -> repair plane


class TestPodFailureDomain:
    def test_pod_exposure_escalates_the_planner(self):
        """All healthy survivors inside ONE pod: a single correlated
        host failure is data loss, so the job is critical even at
        healthy=11 — the same census without pod info is not."""
        h0, h1 = "pod-h0:8080", "pod-h1:8080"
        shards = {sid: h0 for sid in range(11)}
        shards.update({sid: h1 for sid in range(11, 14)})
        pods = {h0: "podA", h1: "podA"}
        planned = planner.plan(
            {900: shards}, stale_nodes=frozenset({h1}), node_pods=pods
        )
        job = planned.jobs[0]
        assert job.pod_exposed and job.critical
        assert job.healthy == 11 > planner.DATA_SHARDS
        assert set(job.rescue) == {11, 12, 13}
        control = planner.plan({900: shards}, stale_nodes=frozenset({h1}))
        assert not control.jobs[0].critical
        assert not control.jobs[0].pod_exposed

    def test_survivors_across_pods_are_not_exposed(self):
        h0, h1 = "pod-h0:8080", "pod-h1:8080"
        shards = {sid: (h0 if sid < 7 else h1) for sid in range(14)}
        pods = {h0: "podA", h1: "podB"}
        planned = planner.plan({901: shards}, node_pods=pods)
        assert not planned.jobs, "healthy volume spread over two pods"
        assert planned.healthy_vids == [901]

    def test_avoid_pods_spreads_and_falls_back(self):
        a1 = SimpleNamespace(mesh_pod="podA")
        a2 = SimpleNamespace(mesh_pod="podA")
        b = SimpleNamespace(mesh_pod="podB")
        solo = SimpleNamespace(mesh_pod="")
        # a podA member already chosen: podA candidates are filtered
        assert _avoid_pods([a2, b, solo], [a1]) == [b, solo]
        # nothing chosen yet (or only pod-less nodes): no filtering
        assert _avoid_pods([a1, a2, b], [solo]) == [a1, a2, b]
        # every candidate shares the chosen pod: fall back to all of
        # them — anti-affinity must never make placement impossible
        assert _avoid_pods([a2], [a1]) == [a2]


# --------------------------------------------------- hedge anti-affinity


@pytest.fixture()
def fresh_policy():
    prev = fp.CONFIG
    fp.PEER_LATENCY.reset()
    fp.RETRY_BUDGETS.reset()
    fp.HEDGE_BUDGET.reset()
    fp.reset_totals()
    yield fp
    fp.configure(prev)
    fp.PEER_LATENCY.reset()
    fp.RETRY_BUDGETS.reset()
    fp.HEDGE_BUDGET.reset()
    fp.reset_totals()


def test_hedge_prefers_spare_outside_the_slow_pod(fresh_policy):
    """When a pod member goes tail-slow its siblings are suspect too
    (one host serves them all), so the hedge spare should come from a
    DIFFERENT pod when one is available."""
    fp.configure(
        fp.FaultPolicyConfig(hedge_quantile=0.95, hedge_budget_pct=100.0)
    )
    peers = {0: "p0", 1: "p1", 2: "p2", 3: "p3"}
    pods = {0: "podA", 1: "podB", 2: "podA", 3: "podB"}
    rng = np.random.default_rng(9)
    # primaries (0, 1) look cheap, spares (2, 3) dearer — sid 0 is
    # deterministically a primary and 2/3 are the spare pool
    for p, base in (("p0", 0.003), ("p1", 0.003), ("p2", 0.006), ("p3", 0.006)):
        for _ in range(30):
            fp.PEER_LATENCY.observe(p, base * (0.75 + 0.5 * rng.random()))
    pool = ThreadPoolExecutor(8)

    def one_slow(sid):
        time.sleep(0.3 if sid == 0 else 0.003)
        return b"d%d" % sid

    res = fp.hedged_gather(
        2, [0, 1, 2, 3], one_slow, pool=pool,
        peer_of=peers.get, pod_of=pods.get,
    )
    pool.shutdown(wait=True)
    assert len(res.got) == 2 and 0 not in res.got
    assert 3 in res.got, "spare must come from outside the slow pod"
    assert 2 not in res.got, "same-pod spare 2 should not be preferred"


# --------------------------------------------------- master health plane


class TestHealthPodTable:
    def test_pod_row_goes_degraded_when_a_member_goes_stale(self):
        ct = ClusterTelemetry(pulse_seconds=1.0)
        for rank, url in enumerate(("h0:8080", "h1:8080")):
            tel = master_pb2.VolumeServerTelemetry(
                mesh_process_id=rank, mesh_process_count=2
            )
            ct.observe(url, tel, now=100.0, mesh_pod="pod0")
        doc = ct.health(now=100.5)
        pod = doc["pods"]["pod0"]
        assert pod["process_count"] == 2
        assert pod["live_members"] == 2
        assert not pod["degraded"]
        # rank 1 stops pulsing (the SIGKILLed member) — past the
        # staleness window its pod row flips to degraded even though
        # rank 0 is still live: one member down stalls the SPMD mesh
        tel0 = master_pb2.VolumeServerTelemetry(
            mesh_process_id=0, mesh_process_count=2
        )
        ct.observe("h0:8080", tel0, now=104.0, mesh_pod="pod0")
        doc = ct.health(now=104.5)
        pod = doc["pods"]["pod0"]
        assert pod["live_members"] == 1
        assert pod["degraded"]
        stale_by_url = {m["url"]: m["stale"] for m in pod["members"]}
        assert stale_by_url == {"h0:8080": False, "h1:8080": True}

    def test_podless_cluster_has_no_pods_key(self):
        ct = ClusterTelemetry(pulse_seconds=1.0)
        ct.observe("solo:8080", None, now=50.0)
        assert "pods" not in ct.health(now=50.5), (
            "single-process health docs must stay byte-identical"
        )


# -------------------------------------------------------- config wiring


class TestMeshConfigValidation:
    def test_multi_process_requires_a_coordinator(self):
        with pytest.raises(ValueError, match="mesh_coordinator"):
            ServingConfig(mesh_process_count=2).validated()

    def test_process_id_must_be_in_range(self):
        with pytest.raises(ValueError, match="mesh_process_id"):
            ServingConfig(
                mesh_process_count=2,
                mesh_coordinator="127.0.0.1:9999",
                mesh_process_id=5,
            ).validated()

    def test_single_process_forbids_nonzero_rank(self):
        with pytest.raises(ValueError, match="mesh_process_id"):
            ServingConfig(mesh_process_id=1).validated()

    def test_bad_coordinator_port_fast_fails(self):
        with pytest.raises(ValueError, match="mesh_coordinator"):
            ServingConfig(
                mesh_process_count=2, mesh_coordinator="hostonly"
            ).validated()

    def test_valid_pod_config_passes(self):
        cfg = ServingConfig(
            mesh_process_count=2,
            mesh_coordinator="10.0.0.1:8476",
            mesh_process_id=1,
        ).validated()
        assert cfg.mesh_process_count == 2
        cfg = ServingConfig().validated()  # single-process default
        assert cfg.mesh_process_count == 1
