"""QoS admission suite (serving/qos.py + the dispatcher seam + the S3
circuit breaker fold-in): tier budgets, deadline-aware shedding, and the
shared trip/recover Breaker — unit-tested with fake clocks and the
FakeStore double, no cluster."""
import asyncio

import pytest

from seaweedfs_tpu import stats
from seaweedfs_tpu.serving import (
    Breaker,
    EcReadDispatcher,
    QosController,
    ServingConfig,
    normalize_tier,
)
from seaweedfs_tpu.serving.qos import (
    BULK,
    INTERACTIVE,
    SHED_BREAKER_OPEN,
    SHED_DEADLINE,
    SHED_QUEUE_BUDGET,
    TierPolicy,
)


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


# ----------------------------------------------------------------- breaker


def test_breaker_trips_after_consecutive_rejections_and_recovers():
    clk = FakeClock()
    b = Breaker(trip_after=3, cooldown_s=5.0, clock=clk)
    assert b.state == Breaker.CLOSED and b.allow()
    b.record_rejection()
    b.record_rejection()
    assert b.state == Breaker.CLOSED  # 2 < trip_after
    b.record_rejection()
    assert b.state == Breaker.OPEN and not b.allow()
    clk.now += 4.9
    assert b.state == Breaker.OPEN  # still cooling down
    clk.now += 0.2
    assert b.state == Breaker.HALF_OPEN and b.allow()  # probe window
    b.record_success()
    assert b.state == Breaker.CLOSED


def test_breaker_failed_probe_reopens_and_fast_fails_dont_extend():
    clk = FakeClock()
    b = Breaker(trip_after=1, cooldown_s=5.0, clock=clk)
    b.record_rejection()
    assert b.state == Breaker.OPEN
    opened = clk.now
    clk.now += 1.0
    # open-state rejections (fast fails) must NOT extend the trip
    b.record_rejection()
    clk.now = opened + 5.1
    assert b.state == Breaker.HALF_OPEN
    b.record_rejection()  # failed probe: fresh cooldown from NOW
    assert b.state == Breaker.OPEN
    clk.now += 4.9
    assert b.state == Breaker.OPEN
    clk.now += 0.2
    assert b.state == Breaker.HALF_OPEN


def test_success_resets_consecutive_count():
    b = Breaker(trip_after=2, cooldown_s=1.0, clock=FakeClock())
    b.record_rejection()
    b.record_success()
    b.record_rejection()
    assert b.state == Breaker.CLOSED  # never 2 consecutive


# ------------------------------------------------------- s3 circuit breaker


def test_s3_circuit_breaker_trips_and_recovers():
    """The satellite contract: the S3 gateway's limit breaker and the
    volume server's QoS share one trip/recover policy (serving.qos.
    Breaker).  Saturating a limit TRIP_AFTER times in a row must trip
    the scope into fast-fail (rejects WITHOUT walking the limit table),
    and the cooldown's half-open probe must recover it."""
    from seaweedfs_tpu.s3api.circuit_breaker import (
        CircuitBreaker,
        CircuitBreakerError,
    )

    cb = CircuitBreaker()
    cb.load(
        b'{"global": {"enabled": true, "actions": {"Read:Count": 1}}}'
    )
    clk = FakeClock()
    cb.breaker("", "Read")._clock = clk  # deterministic cooldown

    hold = cb.acquire("b", "Read", None)  # occupies the whole limit
    for _ in range(CircuitBreaker.TRIP_AFTER):
        with pytest.raises(CircuitBreakerError):
            cb.acquire("b", "Read", None)
    assert cb.breaker("", "Read").state == Breaker.OPEN
    hold()  # capacity free again — but the breaker still fast-fails
    with pytest.raises(CircuitBreakerError, match="breaker open"):
        cb.acquire("b", "Read", None)
    clk.now += CircuitBreaker.RECOVER_S + 0.1
    release = cb.acquire("b", "Read", None)  # half-open probe succeeds
    assert cb.breaker("", "Read").state == Breaker.CLOSED
    release()


def test_s3_circuit_breaker_failed_probe_reopens():
    from seaweedfs_tpu.s3api.circuit_breaker import (
        CircuitBreaker,
        CircuitBreakerError,
    )

    cb = CircuitBreaker()
    cb.load(
        b'{"global": {"enabled": true, "actions": {"Write:Count": 1}}}'
    )
    clk = FakeClock()
    cb.breaker("", "Write")._clock = clk
    hold = cb.acquire("b", "Write", 10)
    for _ in range(CircuitBreaker.TRIP_AFTER):
        with pytest.raises(CircuitBreakerError):
            cb.acquire("b", "Write", 10)
    clk.now += CircuitBreaker.RECOVER_S + 0.1
    # probe while STILL saturated: re-opens for a fresh cooldown
    with pytest.raises(CircuitBreakerError):
        cb.acquire("b", "Write", 10)
    assert cb.breaker("", "Write").state == Breaker.OPEN
    hold()


# ---------------------------------------------------------- qos controller


def _controller(**kw):
    defaults = dict(
        policies={
            INTERACTIVE: TierPolicy(INTERACTIVE, 4, 0.5),
            BULK: TierPolicy(BULK, 2, 0.0),
        },
        trip_after=100,
        cooldown_s=1.0,
    )
    defaults.update(kw)
    return QosController(**defaults)


def test_tier_budget_shed_is_per_tier():
    q = _controller()
    for _ in range(2):
        assert q.admit(BULK, 0, 4) is None
        q.enqueued(BULK)
    # bulk slice is full; interactive is untouched
    assert q.admit(BULK, 2, 4) == SHED_QUEUE_BUDGET
    assert q.admit(INTERACTIVE, 2, 4) is None
    q.dequeued(BULK)
    assert q.admit(BULK, 1, 4) is None


def test_deadline_shed_uses_service_estimate():
    q = _controller()
    # 50ms per needle served depth-1 → 100 queued ≈ 5s wait > 0.5s SLA
    q.observe_service(0.05)
    assert q.admit(INTERACTIVE, 100, 1) == SHED_DEADLINE
    # the same queue drained by 8 lanes estimates under the deadline
    assert q.estimated_wait_s(100, 8) < 1.0
    # bulk has deadline 0 = disabled: never deadline-shed
    assert q.admit(BULK, 100, 1) is None


def test_sustained_sheds_trip_the_breaker_then_fast_fail():
    clk = FakeClock()
    q = _controller(trip_after=3, clock=clk)
    q.observe_service(1.0)
    for _ in range(3):
        assert q.admit(INTERACTIVE, 1000, 1) == SHED_DEADLINE
    # tripped: now fast-fails with the breaker reason, even for an
    # admissible request
    assert q.admit(INTERACTIVE, 0, 1) == SHED_BREAKER_OPEN
    clk.now += 1.1
    assert q.admit(INTERACTIVE, 0, 1) is None  # probe recovers


def test_observe_service_ewma_and_counters():
    q = _controller()
    q.observe_service(0.010)
    q.observe_service(0.020)
    assert 0.010 < q._service_s < 0.020
    g = stats.REGISTRY.get_sample_value
    before = g(
        "SeaweedFS_volumeServer_ec_qos_admitted_total",
        {"tier": "interactive"},
    )
    assert q.admit(INTERACTIVE, 0, 4) is None
    # admitted commits only when the coalescer accepted (enqueued):
    # admit() alone must NOT count — the global backstop can still
    # reject between the two
    assert g(
        "SeaweedFS_volumeServer_ec_qos_admitted_total",
        {"tier": "interactive"},
    ) == before
    q.enqueued(INTERACTIVE)
    assert g(
        "SeaweedFS_volumeServer_ec_qos_admitted_total",
        {"tier": "interactive"},
    ) == before + 1
    q.dequeued(INTERACTIVE)


def test_global_backstop_saturation_feeds_the_breaker():
    """admit() passing and the coalescer then rejecting must count as a
    queue_budget shed AND trip the breaker under sustained saturation —
    the exact overload mode the pre-fix bookkeeping read as success."""
    clk = FakeClock()
    q = _controller(trip_after=3, clock=clk)
    g = stats.REGISTRY.get_sample_value
    shed0 = g(
        "SeaweedFS_volumeServer_ec_qos_shed_total",
        {"tier": "interactive", "reason": "queue_budget"},
    ) or 0
    for _ in range(3):
        assert q.admit(INTERACTIVE, 0, 4) is None
        q.saturated(INTERACTIVE)  # coalescer said no
    assert g(
        "SeaweedFS_volumeServer_ec_qos_shed_total",
        {"tier": "interactive", "reason": "queue_budget"},
    ) == shed0 + 3
    assert q.admit(INTERACTIVE, 0, 4) == SHED_BREAKER_OPEN


def test_normalize_tier():
    assert normalize_tier("bulk") == BULK
    assert normalize_tier("interactive") == INTERACTIVE
    assert normalize_tier("") == INTERACTIVE
    assert normalize_tier(None) == INTERACTIVE
    assert normalize_tier("premium") == INTERACTIVE


def test_serving_config_qos_validation():
    with pytest.raises(ValueError):
        ServingConfig(qos_bulk_queue=0).validated()
    with pytest.raises(ValueError):
        ServingConfig(qos_interactive_deadline_ms=-1).validated()
    with pytest.raises(ValueError):
        ServingConfig(qos_trip_after=0).validated()
    with pytest.raises(ValueError):
        ServingConfig(qos_recover_seconds=0).validated()
    with pytest.raises(ValueError):
        ServingConfig(stall_min_rate_kbps=0).validated()
    cfg = ServingConfig().validated()
    assert cfg.stall_budget_for(0) == cfg.stall_budget_seconds
    assert cfg.stall_budget_for(1 << 20) > cfg.stall_budget_seconds
    assert ServingConfig(stall_budget_seconds=0).stall_budget_for(1) == 0.0


# -------------------------------------------------------- dispatcher seam


class FakeStore:
    def __init__(self):
        self.batch_nids: list[int] = []
        self.native_nids: list[int] = []

    def ec_volume_is_resident(self, vid):
        return True

    def read_ec_needles_batch(
        self, vid, requests, remote_read=None, zero_copy=False
    ):
        self.batch_nids.extend(nid for nid, _ in requests)
        return [f"n-{nid}".encode() for nid, _ in requests]

    def read_ec_needle(
        self, vid, nid, cookie=None, remote_read=None, use_device=True,
        zero_copy=False,
    ):
        self.native_nids.append(nid)
        return f"n-{nid}".encode()


def test_dispatcher_sheds_bulk_tier_to_native_keeps_interactive():
    """A bulk flood past its tier budget must shed to the host path
    while interactive reads keep riding the batched queue — and both
    must return correct bytes."""
    store = FakeStore()

    async def go():
        d = EcReadDispatcher(
            store, lambda vid: None,
            ServingConfig(
                max_inflight=1, max_wait_us=0, qos_bulk_queue=1,
            ),
        )
        # seed the lane with a slow-ish first batch so the queue holds
        d.qos.enqueued("bulk")  # bulk slice now full
        got = await asyncio.gather(
            d.read(1, 1, None, tier="bulk"),
            d.read(1, 2, None, tier="interactive"),
        )
        assert got == [b"n-1", b"n-2"]
        assert 1 in store.native_nids  # bulk shed to host path
        assert 2 in store.batch_nids  # interactive rode the queue

    asyncio.run(go())


def test_dispatcher_qos_disabled_skips_admission():
    store = FakeStore()

    async def go():
        d = EcReadDispatcher(
            store, lambda vid: None,
            ServingConfig(max_inflight=1, max_wait_us=0, qos=False),
        )
        d.qos.enqueued("bulk")  # would shed if qos were consulted
        assert await d.read(1, 5, None, tier="bulk") == b"n-5"
        assert 5 in store.batch_nids

    asyncio.run(go())


def test_dispatcher_s3_origin_attribution():
    store = FakeStore()
    g = stats.REGISTRY.get_sample_value

    async def go():
        d = EcReadDispatcher(
            store, lambda vid: None,
            ServingConfig(max_inflight=1, max_wait_us=0),
        )
        b0 = g(
            "SeaweedFS_volumeServer_ec_read_route_total",
            {"route": "s3_batched"},
        ) or 0
        admit0 = g(
            "SeaweedFS_volumeServer_ec_read_route_total",
            {"route": "batched"},
        ) or 0
        assert await d.read(1, 7, None, origin="s3") == b"n-7"
        # attribution is IN ADDITION to the admitting route
        assert g(
            "SeaweedFS_volumeServer_ec_read_route_total",
            {"route": "s3_batched"},
        ) == b0 + 1
        assert g(
            "SeaweedFS_volumeServer_ec_read_route_total",
            {"route": "batched"},
        ) == admit0 + 1

    asyncio.run(go())
