"""SQL query engine + S3 SelectObjectContent e2e (reference: weed/query
experimental SELECT; AWS event-stream framing on the wire).
"""
import asyncio

import pytest

from seaweedfs_tpu.query import QueryError, run_select
from seaweedfs_tpu.s3api.select import parse_event_stream
from seaweedfs_tpu.server.cluster import LocalCluster
from tests.test_s3 import S3Client

CSV = b"""name,dept,salary
ann,eng,120
bob,sales,90
cal,eng,150
dee,ops,80
"""

JSONL = (
    b'{"name": "ann", "dept": "eng", "salary": 120}\n'
    b'{"name": "bob", "dept": "sales", "salary": 90}\n'
    b'{"name": "cal", "dept": "eng", "salary": 150}\n'
)


def test_select_csv_where_and_projection():
    out = run_select(
        "SELECT name, salary FROM S3Object s WHERE s.dept = 'eng'",
        CSV, "csv", True, "csv",
    )
    assert out == b"ann,120\ncal,150\n"
    # numeric comparison, not lexicographic
    out = run_select(
        "SELECT name FROM S3Object WHERE salary > 100", CSV, "csv", True, "csv"
    )
    assert out == b"ann\ncal\n"
    # positional columns without header
    out = run_select(
        "SELECT _1 FROM S3Object WHERE _3 = '90'",
        b"x,eng,120\ny,sales,90\n", "csv", False, "csv",
    )
    assert out == b"y\n"
    # SELECT * emits each column exactly once
    assert run_select(
        "SELECT * FROM S3Object LIMIT 1", CSV, "csv", "use", "csv"
    ) == b"ann,eng,120\n"
    # FileHeaderInfo=IGNORE skips the header but keeps positional columns
    assert run_select(
        "SELECT _1 FROM S3Object", CSV, "csv", "ignore", "csv"
    ) == b"ann\nbob\ncal\ndee\n"
    # quoted literals containing ' and ' survive the WHERE split
    assert run_select(
        "SELECT name FROM S3Object WHERE dept = 'a and b' AND salary = '1'",
        b"name,dept,salary\nx,a and b,1\ny,eng,1\n", "csv", "use", "csv",
    ) == b"x\n"
    # limit + count
    assert run_select(
        "SELECT * FROM S3Object LIMIT 2", CSV, "csv", True, "csv"
    ).count(b"\n") == 2
    assert run_select(
        "SELECT COUNT(*) FROM S3Object WHERE dept = 'eng'",
        CSV, "csv", True, "csv",
    ) == b"2\n"


def test_select_json_and_errors():
    out = run_select(
        "SELECT name FROM S3Object s WHERE s.salary >= 120 AND s.dept = 'eng'",
        JSONL, "json", False, "json",
    )
    assert out == b'{"name": "ann"}\n{"name": "cal"}\n'
    with pytest.raises(QueryError):
        run_select("DROP TABLE S3Object", CSV)
    with pytest.raises(QueryError):
        run_select("SELECT nope FROM S3Object", CSV, "csv", True)


def test_s3_select_object_content(tmp_path):
    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, with_s3=True
        )
        await cluster.start()
        try:
            c = S3Client(cluster.s3.url)
            await c.request("PUT", "/lake")
            await c.request("PUT", "/lake/people.csv", CSV)
            req = (
                "<SelectObjectContentRequest>"
                "<Expression>SELECT name FROM S3Object s WHERE s.dept = 'eng'"
                "</Expression><ExpressionType>SQL</ExpressionType>"
                "<InputSerialization><CSV><FileHeaderInfo>USE</FileHeaderInfo>"
                "</CSV></InputSerialization>"
                "<OutputSerialization><CSV/></OutputSerialization>"
                "</SelectObjectContentRequest>"
            ).encode()
            st, body, _ = await c.request(
                "POST", "/lake/people.csv", req, query="select&select-type=2"
            )
            assert st == 200, body
            events = list(parse_event_stream(body))
            types = [h[":event-type"] for h, _ in events]
            assert types == ["Records", "Stats", "End"], types
            assert events[0][1] == b"ann\ncal\n"
            assert b"<BytesScanned>" in events[1][1]

            # bad SQL -> InvalidRequest
            bad = req.replace(b"SELECT name FROM S3Object s WHERE s.dept = 'eng'", b"DELETE EVERYTHING")
            st, body, _ = await c.request(
                "POST", "/lake/people.csv", bad, query="select&select-type=2"
            )
            assert st == 400
        finally:
            await cluster.stop()

    asyncio.run(go())
