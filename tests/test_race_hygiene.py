"""Concurrency-hygiene gate: the Python analogue of the reference CI's
`-race` e2e (build with -race, run fio, grep logs for DATA RACE —
.github/workflows/e2e.yml:40-105).  Python's races surface as asyncio
debug findings instead: coroutines never awaited, task exceptions never
retrieved, and error-level logs out of the server loops.  This test runs
a deliberately concurrent mixed workload against a live cluster with
asyncio debug mode on and fails on any of those findings."""
import asyncio
import logging
import os
import warnings

import aiohttp
import pytest

from seaweedfs_tpu.server.cluster import LocalCluster


class _Collector(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.ERROR)
        self.records: list[str] = []

    def emit(self, record):
        # server loops must not leak unhandled exceptions under load
        self.records.append(f"{record.name}: {record.getMessage()}")


def test_concurrent_workload_is_clean(tmp_path):
    collector = _Collector()

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=2, with_filer=True,
            pulse_seconds=1,
        )
        await cluster.start()
        try:
            base = f"http://{cluster.filer.url}"
            payloads = {
                f"/load/f{i:03d}.bin": os.urandom(1024 * (1 + i % 64))
                for i in range(96)
            }
            async with aiohttp.ClientSession() as s:

                async def writer(path, data):
                    async with s.put(base + path, data=data) as r:
                        assert r.status in (200, 201)

                async def reader(path, data):
                    for _ in range(3):
                        async with s.get(base + path) as r:
                            if r.status == 200:
                                assert await r.read() == data
                                return
                            await asyncio.sleep(0.05)

                async def deleter(path):
                    async with s.delete(base + path) as r:
                        assert r.status < 500

                await asyncio.gather(
                    *(writer(p, d) for p, d in payloads.items())
                )
                items = list(payloads.items())
                await asyncio.gather(
                    *(reader(p, d) for p, d in items[:48]),
                    *(writer(p, d + b"!") for p, d in items[48:72]),
                    *(deleter(p) for p, _ in items[72:]),
                )
        finally:
            await cluster.stop()
        # let any stray callbacks fire before the loop closes
        await asyncio.sleep(0.2)

    root = logging.getLogger()
    root.addHandler(collector)
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            asyncio.run(go(), debug=True)
    finally:
        root.removeHandler(collector)

    never_awaited = [
        str(w.message) for w in caught
        if "was never awaited" in str(w.message)
    ]
    assert not never_awaited, never_awaited
    # "Task exception was never retrieved" arrives via the asyncio logger
    # at ERROR level -> the collector; so do unhandled server errors
    leaks = [
        r for r in collector.records
        if "never retrieved" in r or "Unhandled" in r or "exception" in r.lower()
    ]
    assert not leaks, leaks
