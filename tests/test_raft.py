"""Raft core: election, replication, leader failover, durable restart.

Reference role: weed/server/raft_server.go (hashicorp/raft behaviors the
masters rely on).  Three in-process nodes over real grpc.aio servers.
"""
import asyncio

import grpc
import pytest

from seaweedfs_tpu.pb import generic_handler, raft_pb2
from seaweedfs_tpu.pb.rpc import GRPC_OPTIONS
from seaweedfs_tpu.raft import RaftNode
from seaweedfs_tpu.raft.node import LEADER, NotLeader


def run(coro):
    return asyncio.run(coro)


class Harness:
    def __init__(self, tmp_path, n=3, snapshot_threshold=None):
        self.tmp_path = tmp_path
        self.n = n
        self.snapshot_threshold = snapshot_threshold
        self.nodes: dict[str, RaftNode] = {}
        self.servers: dict[str, grpc.aio.Server] = {}
        self.applied: dict[str, list] = {}
        self.restored: dict[str, dict] = {}
        self.base_counts: dict[str, int] = {}
        self.addrs: list[str] = []

    def _snapshot_of(self, addr):
        return {
            "count": self.base_counts.get(addr, 0) + len(self.applied[addr])
        }

    def _restore(self, addr, st):
        self.restored[addr] = st
        self.base_counts[addr] = st["count"]

    async def start(self):
        # reserve ports first so peers lists are complete
        for i in range(self.n):
            server = grpc.aio.server(options=GRPC_OPTIONS)
            port = server.add_insecure_port("127.0.0.1:0")
            addr = f"127.0.0.1:{port}"
            self.addrs.append(addr)
            self.servers[addr] = server
        for i, addr in enumerate(self.addrs):
            await self.spawn(i, addr, fresh=True)

    async def spawn(self, i, addr, fresh=False, **node_kwargs):
        if not fresh:
            server = grpc.aio.server(options=GRPC_OPTIONS)
            server.add_insecure_port(addr)
            self.servers[addr] = server
        self.applied.setdefault(addr, [])
        if self.snapshot_threshold is not None:
            node_kwargs.setdefault("snapshot_threshold", self.snapshot_threshold)
            node_kwargs.setdefault(
                "snapshot_fn", lambda a=addr: self._snapshot_of(a)
            )
            node_kwargs.setdefault(
                "restore_fn", lambda st, a=addr: self._restore(a, st)
            )
        node = RaftNode(
            addr, list(self.addrs),
            apply_fn=lambda cmd, a=addr, **kw: self.applied[a].append(cmd),
            data_dir=str(self.tmp_path / f"raft-{i}"),
            election_timeout=(0.15, 0.3),
            heartbeat_interval=0.04,
            **node_kwargs,
        )
        self.nodes[addr] = node
        self.servers[addr].add_generic_rpc_handlers(
            [generic_handler(raft_pb2, "SeaweedRaft", node)]
        )
        await self.servers[addr].start()
        await node.start()
        return node

    async def kill(self, addr):
        await self.nodes[addr].stop()
        await self.servers[addr].stop(0.1)
        del self.nodes[addr]
        del self.servers[addr]

    async def stop(self):
        for addr in list(self.nodes):
            await self.kill(addr)

    async def wait_leader(self, timeout=5.0) -> RaftNode:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            leaders = [n for n in self.nodes.values() if n.state == LEADER]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.05)
        raise TimeoutError("no single leader emerged")


def test_election_replication_failover(tmp_path):
    async def go():
        h = Harness(tmp_path)
        await h.start()
        try:
            leader = await h.wait_leader()
            for i in range(5):
                await leader.propose({"op": "set", "i": i})
            await asyncio.sleep(0.3)  # followers catch up via heartbeat
            for addr, node in h.nodes.items():
                assert h.applied[addr] == [
                    {"op": "set", "i": i} for i in range(5)
                ], addr

            # follower refuses proposals and names the leader
            follower = next(
                n for n in h.nodes.values() if n.state != LEADER
            )
            with pytest.raises(NotLeader) as ei:
                await follower.propose({"op": "nope"})
            assert ei.value.leader == leader.id

            # kill the leader: a new one takes over and the log continues
            old = leader.id
            await h.kill(leader.id)
            leader2 = await h.wait_leader()
            assert leader2.id != old
            await leader2.propose({"op": "after-failover"})
            await asyncio.sleep(0.3)
            for addr, node in h.nodes.items():
                assert h.applied[addr][-1] == {"op": "after-failover"}, addr
        finally:
            await h.stop()

    run(go())


def test_restart_recovers_durable_state(tmp_path):
    async def go():
        h = Harness(tmp_path)
        await h.start()
        try:
            leader = await h.wait_leader()
            for i in range(3):
                await leader.propose({"n": i})
            await asyncio.sleep(0.3)
            # restart a follower from disk: it must re-apply the log
            follower = next(n for n in h.nodes.values() if n.state != LEADER)
            addr = follower.id
            idx = h.addrs.index(addr)
            await h.kill(addr)
            h.applied[addr] = []
            node = await h.spawn(idx, addr)
            await asyncio.sleep(0.4)
            assert [c["n"] for c in h.applied[addr]] == [0, 1, 2]
            assert node.term >= leader.term
        finally:
            await h.stop()

    run(go())


def test_snapshot_compacts_log_and_restart_replays_tail(tmp_path):
    """Past the threshold the log is replaced by a snapshot; a restart
    replays O(snapshot)+tail instead of the whole history (VERDICT
    round-2 'done' condition for raft snapshots)."""

    async def go():
        h = Harness(tmp_path, n=1, snapshot_threshold=50)
        await h.start()
        try:
            (leader,) = h.nodes.values()
            total = 300
            for i in range(total):
                await leader.propose({"n": i})
            addr = leader.id
            # the log was compacted — far below the command count
            assert len(leader.log) - 1 <= 60, len(leader.log)
            assert leader.snapshot_index > 0
            assert len(h.applied[addr]) == total

            # restart from disk: restore_fn gets the snapshot, and only
            # the tail beyond it re-applies
            await h.kill(addr)
            h.applied[addr] = []
            h.base_counts.pop(addr, None)
            node = await h.spawn(0, addr)
            await asyncio.sleep(0.5)
            assert addr in h.restored, "restart never restored a snapshot"
            replayed = len(h.applied[addr])
            assert replayed <= 60, f"replayed {replayed} entries"
            assert h.restored[addr]["count"] + replayed == total
            assert node.snapshot_index > 0
        finally:
            await h.stop()

    run(go())


def test_lagging_follower_catches_up_via_installsnapshot(tmp_path):
    """A wiped/joining follower whose needed entries were compacted away
    receives the leader's snapshot, then the tail."""

    async def go():
        h = Harness(tmp_path, n=3, snapshot_threshold=20)
        await h.start()
        try:
            leader = await h.wait_leader()
            victim = next(a for a in h.addrs if a != leader.id)
            vidx = h.addrs.index(victim)
            await h.kill(victim)

            total = 120
            for i in range(total):
                await leader.propose({"n": i})
            assert leader.snapshot_index > 0

            # wipe the victim's disk: it returns knowing nothing
            import shutil

            shutil.rmtree(str(tmp_path / f"raft-{vidx}"))
            h.applied[victim] = []
            h.base_counts.pop(victim, None)
            await h.spawn(vidx, victim)

            deadline = asyncio.get_event_loop().time() + 8
            while True:
                have = h.base_counts.get(victim, 0) + len(h.applied[victim])
                if have == total and victim in h.restored:
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(
                        f"victim has {have}/{total}, restored="
                        f"{victim in h.restored}"
                    )
                await asyncio.sleep(0.1)
            # and it keeps up with NEW entries after the snapshot.  The
            # rejoining node's election-timeout campaign may have bumped
            # the term and moved leadership (no pre-vote here, like raft
            # without the §9.6 extension) — re-acquire the leader.
            leader = await h.wait_leader()
            await leader.propose({"n": total})
            await asyncio.sleep(0.3)
            assert h.applied[victim][-1] == {"n": total}
        finally:
            await h.stop()

    run(go())
