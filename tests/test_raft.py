"""Raft core: election, replication, leader failover, durable restart.

Reference role: weed/server/raft_server.go (hashicorp/raft behaviors the
masters rely on).  Three in-process nodes over real grpc.aio servers.
"""
import asyncio

import grpc
import pytest

from seaweedfs_tpu.pb import generic_handler, raft_pb2
from seaweedfs_tpu.pb.rpc import GRPC_OPTIONS
from seaweedfs_tpu.raft import RaftNode
from seaweedfs_tpu.raft.node import LEADER, NotLeader


def run(coro):
    return asyncio.run(coro)


class Harness:
    def __init__(self, tmp_path, n=3):
        self.tmp_path = tmp_path
        self.n = n
        self.nodes: dict[str, RaftNode] = {}
        self.servers: dict[str, grpc.aio.Server] = {}
        self.applied: dict[str, list] = {}
        self.addrs: list[str] = []

    async def start(self):
        # reserve ports first so peers lists are complete
        for i in range(self.n):
            server = grpc.aio.server(options=GRPC_OPTIONS)
            port = server.add_insecure_port("127.0.0.1:0")
            addr = f"127.0.0.1:{port}"
            self.addrs.append(addr)
            self.servers[addr] = server
        for i, addr in enumerate(self.addrs):
            await self.spawn(i, addr, fresh=True)

    async def spawn(self, i, addr, fresh=False):
        if not fresh:
            server = grpc.aio.server(options=GRPC_OPTIONS)
            server.add_insecure_port(addr)
            self.servers[addr] = server
        self.applied.setdefault(addr, [])
        node = RaftNode(
            addr, list(self.addrs),
            apply_fn=lambda cmd, a=addr, **kw: self.applied[a].append(cmd),
            data_dir=str(self.tmp_path / f"raft-{i}"),
            election_timeout=(0.15, 0.3),
            heartbeat_interval=0.04,
        )
        self.nodes[addr] = node
        self.servers[addr].add_generic_rpc_handlers(
            [generic_handler(raft_pb2, "SeaweedRaft", node)]
        )
        await self.servers[addr].start()
        await node.start()
        return node

    async def kill(self, addr):
        await self.nodes[addr].stop()
        await self.servers[addr].stop(0.1)
        del self.nodes[addr]
        del self.servers[addr]

    async def stop(self):
        for addr in list(self.nodes):
            await self.kill(addr)

    async def wait_leader(self, timeout=5.0) -> RaftNode:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            leaders = [n for n in self.nodes.values() if n.state == LEADER]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.05)
        raise TimeoutError("no single leader emerged")


def test_election_replication_failover(tmp_path):
    async def go():
        h = Harness(tmp_path)
        await h.start()
        try:
            leader = await h.wait_leader()
            for i in range(5):
                await leader.propose({"op": "set", "i": i})
            await asyncio.sleep(0.3)  # followers catch up via heartbeat
            for addr, node in h.nodes.items():
                assert h.applied[addr] == [
                    {"op": "set", "i": i} for i in range(5)
                ], addr

            # follower refuses proposals and names the leader
            follower = next(
                n for n in h.nodes.values() if n.state != LEADER
            )
            with pytest.raises(NotLeader) as ei:
                await follower.propose({"op": "nope"})
            assert ei.value.leader == leader.id

            # kill the leader: a new one takes over and the log continues
            old = leader.id
            await h.kill(leader.id)
            leader2 = await h.wait_leader()
            assert leader2.id != old
            await leader2.propose({"op": "after-failover"})
            await asyncio.sleep(0.3)
            for addr, node in h.nodes.items():
                assert h.applied[addr][-1] == {"op": "after-failover"}, addr
        finally:
            await h.stop()

    run(go())


def test_restart_recovers_durable_state(tmp_path):
    async def go():
        h = Harness(tmp_path)
        await h.start()
        try:
            leader = await h.wait_leader()
            for i in range(3):
                await leader.propose({"n": i})
            await asyncio.sleep(0.3)
            # restart a follower from disk: it must re-apply the log
            follower = next(n for n in h.nodes.values() if n.state != LEADER)
            addr = follower.id
            idx = h.addrs.index(addr)
            await h.kill(addr)
            h.applied[addr] = []
            node = await h.spawn(idx, addr)
            await asyncio.sleep(0.4)
            assert [c["n"] for c in h.applied[addr]] == [0, 1, 2]
            assert node.term >= leader.term
        finally:
            await h.stop()

    run(go())
