"""Byte-compatibility tests against the reference's committed binary volume
fixture (/root/reference/weed/storage/erasure_coding/1.dat + 1.idx — the same
files ec_test.go:21-207 runs TestEncodingDecoding over).

The fixture is a real-world v3 volume whose needles store the legacy *masked*
CRC (needle/crc.go:25-27), so it exercises exactly the read-compat path that
synthetic self-generated volumes cannot: every needle must parse, EC-encode,
and degraded-read back through our interval math and GF(256) reconstruction.
"""
import os
import random
import shutil

import pytest

from seaweedfs_tpu.storage import ec, idx
from seaweedfs_tpu.storage.needle import mask_crc
from seaweedfs_tpu.storage.volume import Volume

FIXTURE_DIR = "/root/reference/weed/storage/erasure_coding"

# class-level (not module-level): the needle-volume test below has its own
# fixture and must not be masked when only the EC fixture is absent
ec_fixture_required = pytest.mark.skipif(
    not os.path.exists(os.path.join(FIXTURE_DIR, "1.dat")),
    reason="reference fixture not available",
)


@pytest.fixture
def fixture_volume(tmp_path):
    for ext in (".dat", ".idx"):
        shutil.copy(os.path.join(FIXTURE_DIR, "1" + ext), tmp_path / ("1" + ext))
    os.chmod(tmp_path / "1.dat", 0o644)
    os.chmod(tmp_path / "1.idx", 0o644)
    v = Volume(str(tmp_path), 1)
    yield v
    v.close()


def live_entries(idx_path):
    """Latest entry per needle id, tombstones dropped (CompactMap replay)."""
    latest = {}
    for nid, off, size in idx.walk(idx_path):
        latest[nid] = (off, size)
    return {nid: os for nid, os in latest.items() if os[1] >= 0}


@ec_fixture_required
class TestFixtureVolume:
    def test_all_needles_readable(self, fixture_volume, tmp_path):
        entries = live_entries(str(tmp_path / "1.idx"))
        assert len(entries) > 200, "fixture should hold hundreds of needles"
        read = 0
        for nid in entries:
            n = fixture_volume.read(nid)  # raises CrcError before the fix
            assert n.id == nid
            read += 1
        assert read == len(entries)

    def test_fixture_stores_masked_crcs(self, fixture_volume, tmp_path):
        """Sanity: this fixture really does store CRC.Value() checksums, so
        it regression-guards the masked-accept path (needle_read.go:74-78).
        Note from_bytes normalizes n.checksum to the raw CRC on success, so
        we inspect the on-disk footer directly."""
        import struct

        from seaweedfs_tpu.ops.crc import crc32c
        from seaweedfs_tpu.storage import types as t

        entries = live_entries(str(tmp_path / "1.idx"))
        nid, (off, size) = next(iter(sorted(entries.items())))
        n = fixture_volume.read(nid)
        with open(tmp_path / "1.dat", "rb") as f:
            f.seek(off + t.NEEDLE_HEADER_SIZE + size)
            (stored,) = struct.unpack(">I", f.read(4))
        assert stored == mask_crc(crc32c(n.data))
        assert stored != crc32c(n.data)

    def test_ec_encode_and_full_read(self, fixture_volume, tmp_path):
        entries = live_entries(str(tmp_path / "1.idx"))
        base = Volume.base_name(str(tmp_path), 1)
        ec.write_ec_files(base, backend="cpu")
        ec.write_sorted_file_from_idx(base)
        ev = ec.EcVolume(str(tmp_path), 1)
        for i in range(14):
            ev.add_shard(i)
        for nid in entries:
            want = fixture_volume.read(nid)
            got = ev.read_needle(nid)
            assert got.data == want.data, f"needle {nid:x} mismatch via EC"
        ev.close()

    def test_degraded_read_two_shards_down(self, fixture_volume, tmp_path):
        """The ec_test.go:143-174 shape on the real fixture: drop shards,
        reconstruct every needle from the survivors."""
        entries = live_entries(str(tmp_path / "1.idx"))
        base = Volume.base_name(str(tmp_path), 1)
        ec.write_ec_files(base, backend="cpu")
        ec.write_sorted_file_from_idx(base)
        rng = random.Random(42)
        for _ in range(2):
            down = set(rng.sample(range(14), 2))
            ev = ec.EcVolume(str(tmp_path), 1)
            for i in range(14):
                if i not in down:
                    ev.add_shard(i)
            for nid in entries:
                want = fixture_volume.read(nid)
                got = ev.read_needle(nid)
                assert got.data == want.data, (
                    f"needle {nid:x} mismatch, shards {sorted(down)} down"
                )
            ev.close()

    def test_decode_back_to_dat(self, fixture_volume, tmp_path):
        """ec.decode reassembles a .dat whose live needles byte-match the
        original fixture records (ec_decoder shape, ec_decoder.go:154-201)."""
        entries = live_entries(str(tmp_path / "1.idx"))
        base = Volume.base_name(str(tmp_path), 1)
        with open(base + ".dat", "rb") as f:
            original = f.read()
        ec.write_ec_files(base, backend="cpu")
        ec.write_sorted_file_from_idx(base)
        os.rename(base + ".dat", base + ".dat.orig")
        os.rename(base + ".idx", base + ".idx.orig")
        ec.write_dat_file(base, len(original))
        with open(base + ".dat", "rb") as f:
            rebuilt = f.read()
        assert rebuilt == original
        assert len(entries) > 0


NEEDLE_FIXTURE = "/root/reference/weed/storage/needle/43.dat"


@pytest.mark.skipif(
    not os.path.exists(NEEDLE_FIXTURE), reason="reference fixture not available"
)
def test_reference_needle_volume_reindexes_and_reads(tmp_path):
    """43.dat is a reference-written v3 volume committed WITHOUT its .idx:
    opening it exercises the reindex-from-.dat recovery path on real
    reference bytes (CRC verify + record walking), and the recovered
    needle must read back clean."""
    shutil.copy(NEEDLE_FIXTURE, tmp_path / "43.dat")
    os.chmod(tmp_path / "43.dat", 0o644)
    v = Volume(str(tmp_path), 43)
    try:
        assert v.version == 3
        assert len(v.nm) >= 1, "recovery must reindex the reference needle"
        nid = next(iter(v.nm.items()))[0]
        n = v.read(nid)
        assert n.id == nid
        assert len(n.data) > 0
        assert n.data[:2] == b"PK", "fixture payload is a zip archive"
        # the rebuilt index round-trips: reopen reads the same needle
        v.close()
        v2 = Volume(str(tmp_path), 43)
        assert v2.read(nid).data == n.data
        v2.close()
    finally:
        try:
            v.close()
        # graftlint: allow(no-silent-swallow): best-effort v.close()
        # of a volume the test may have already closed
        except Exception:
            pass
