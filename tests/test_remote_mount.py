"""Remote storage mounts: mirror an external object store into the filer
namespace, stream reads through the backend, cache to local chunks,
uncache back to remote-only.

Reference: weed/shell/command_remote_mount.go/_cache.go/_uncache.go +
weed/remote_storage.
"""
import asyncio
import io
import os

import aiohttp
import pytest

from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.storage import backend as backend_mod


def run(coro):
    return asyncio.run(coro)


def test_remote_mount_cache_uncache(tmp_path):
    # fabricate the "external" object store
    ext = tmp_path / "external"
    (ext / "photos").mkdir(parents=True)
    objects = {
        "photos/a.jpg": os.urandom(50_000),
        "photos/deep/b.bin": os.urandom(120_000),
        "top.txt": b"hello remote world",
    }
    for key, data in objects.items():
        p = ext / key
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(data)

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path / "c"), n_volume_servers=1, with_filer=True
        )
        await cluster.start()
        try:
            env = CommandEnv(
                [cluster.master.advertise_url], out=io.StringIO()
            )
            await run_command(env, "lock")
            # remote.configure needs a registered filer; registration is
            # asynchronous after cluster.start()
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                try:
                    await env.find_filer()
                    break
                except RuntimeError:
                    await asyncio.sleep(0.1)
            await run_command(
                env, f"remote.configure -name local.ext -dir {ext}"
            )
            await run_command(env, "remote.mount -dir /mnt/ext -remote local.ext/")
            assert "3 objects" in env.out.getvalue()

            base = f"http://{cluster.filer.url}"

            async def get(path):
                async with aiohttp.ClientSession() as s:
                    async with s.get(base + path) as r:
                        return r.status, await r.read()

            # reads stream through the backend (no chunks yet)
            st, body = await get("/mnt/ext/top.txt")
            assert st == 200 and body == objects["top.txt"]
            st, body = await get("/mnt/ext/photos/deep/b.bin")
            assert st == 200 and body == objects["photos/deep/b.bin"]
            e = cluster.filer.filer.find_entry("/mnt/ext/photos/a.jpg")
            assert not e.chunks and e.extended["remote.key"] == b"photos/a.jpg"

            # range read through the remote
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    base + "/mnt/ext/photos/a.jpg",
                    headers={"Range": "bytes=1000-1999"},
                ) as r:
                    assert r.status == 206
                    assert await r.read() == objects["photos/a.jpg"][1000:2000]

            # cache: entries gain chunks; contents identical
            await run_command(env, "remote.cache -dir /mnt/ext")
            assert "cached 3 objects" in env.out.getvalue()
            e = cluster.filer.filer.find_entry("/mnt/ext/photos/a.jpg")
            assert e.chunks and e.extended.get("remote.key") == b"photos/a.jpg"
            st, body = await get("/mnt/ext/photos/a.jpg")
            assert st == 200 and body == objects["photos/a.jpg"]

            # uncache: chunks dropped, remote read-through again
            await run_command(env, "remote.uncache -dir /mnt/ext")
            e = cluster.filer.filer.find_entry("/mnt/ext/photos/a.jpg")
            assert not e.chunks
            st, body = await get("/mnt/ext/photos/a.jpg")
            assert st == 200 and body == objects["photos/a.jpg"]

            # unmount removes the mirror; the external store is untouched
            await run_command(env, "remote.unmount -dir /mnt/ext")
            st, _ = await get("/mnt/ext/top.txt")
            assert st == 404
            assert (ext / "top.txt").read_bytes() == objects["top.txt"]
        finally:
            await cluster.stop()

    run(go())
