"""Chaos e2e for the self-healing repair plane (seaweedfs_tpu/repair):
a real in-process cluster, real faults from the chaos harness
(loadgen/chaos.py), and the master's autonomous scheduler closing the
loop the reference leaves to a human in `weed shell`:

  * kill a volume server mid-operation -> the scheduler detects the
    missing shards and re-converges to all 14, byte-verified reads
    throughout;
  * corrupt a parity shard on disk -> the master-driven scrub sweep
    localizes it, the corrupt copy is dropped BEFORE the rebuild, and
    the volume returns to full redundancy;
  * partition a holder's heartbeats -> the node goes STALE and the
    scheduler re-establishes its shards on fresh nodes without
    gathering from the suspect;

plus the operator surface: the repair block of /cluster/health.json
and the volume.repair.status / pause / resume shell verbs.
"""
import asyncio
import io
import os
import time

import aiohttp
import numpy as np
import pytest

from seaweedfs_tpu.loadgen import ChaosInjector
from seaweedfs_tpu.operation import assign, upload_data
from seaweedfs_tpu.pb import Stub, channel, volume_server_pb2
from seaweedfs_tpu.repair import RepairConfig
from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.storage.ec import TOTAL_SHARDS


def run(coro):
    return asyncio.run(coro)


async def fetch(url):
    async with aiohttp.ClientSession() as s:
        async with s.get(url) as r:
            return r.status, await r.read()


def _vs_stub(vs):
    return Stub(channel(vs.grpc_url), volume_server_pb2, "VolumeServer")


async def _fill_one_volume(cluster, n_blobs=10):
    """Write blobs until one volume holds `n_blobs`; returns (vid,
    {fid: bytes})."""
    master = cluster.master.advertise_url
    rng = np.random.default_rng(41)
    blobs, vid = {}, None
    for i in range(n_blobs * 12):
        if len(blobs) >= n_blobs:
            break
        a = await assign(master)
        v = int(a.fid.split(",")[0])
        if vid is None:
            vid = v
        if v != vid:
            continue
        data = rng.integers(0, 256, 1200 + i * 97, dtype=np.uint8).tobytes()
        await upload_data(f"http://{a.url}/{a.fid}", data)
        blobs[a.fid] = data
    assert len(blobs) >= max(4, n_blobs // 2)
    return vid, blobs


async def _encode_and_spread(cluster, vid, spread=True):
    """EC-encode `vid` on its holder; when `spread`, distribute the 14
    shards over all servers (holder keeps the first group).  Returns
    the holder server."""
    holder = next(
        vs for vs in cluster.volume_servers if vs.store.has_volume(vid)
    )
    stub = _vs_stub(holder)
    await stub.VolumeMarkReadonly(
        volume_server_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
    )
    await stub.VolumeEcShardsGenerate(
        volume_server_pb2.VolumeEcShardsGenerateRequest(volume_id=vid)
    )
    await stub.VolumeEcShardsMount(
        volume_server_pb2.VolumeEcShardsMountRequest(
            volume_id=vid, shard_ids=list(range(TOTAL_SHARDS))
        )
    )
    if spread:
        others = [vs for vs in cluster.volume_servers if vs is not holder]
        per = TOTAL_SHARDS // (len(others) + 1)
        start = TOTAL_SHARDS - per * len(others)
        for j, vs in enumerate(others):
            sids = list(range(start + j * per, start + (j + 1) * per))
            peer = _vs_stub(vs)
            await peer.VolumeEcShardsCopy(
                volume_server_pb2.VolumeEcShardsCopyRequest(
                    volume_id=vid, shard_ids=sids,
                    copy_ecx_file=True, copy_ecj_file=True,
                    copy_vif_file=True,
                    source_data_node=holder.grpc_url,
                )
            )
            await peer.VolumeEcShardsMount(
                volume_server_pb2.VolumeEcShardsMountRequest(
                    volume_id=vid, shard_ids=sids
                )
            )
            await stub.VolumeEcShardsUnmount(
                volume_server_pb2.VolumeEcShardsUnmountRequest(
                    volume_id=vid, shard_ids=sids
                )
            )
            for sid in sids:
                p = holder.store._ec_base(vid, "") + f".ec{sid:02d}"
                if os.path.exists(p):
                    os.remove(p)
    await stub.VolumeUnmount(
        volume_server_pb2.VolumeUnmountRequest(volume_id=vid)
    )
    return holder


def _held_sids(master, vid, exclude_urls=()) -> set:
    locs = master.topo.lookup_ec_shards(vid)
    if locs is None:
        return set()
    return {
        sid for sid, nodes in enumerate(locs.locations)
        if any(n.url not in exclude_urls for n in nodes)
    }


async def _wait_full_redundancy(
    master, vid, timeout=30.0, exclude_urls=()
) -> float:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if len(_held_sids(master, vid, exclude_urls)) == TOTAL_SHARDS:
            return time.monotonic() - t0
        await asyncio.sleep(0.2)
    raise TimeoutError(
        f"volume {vid} never reached full redundancy: "
        f"{sorted(_held_sids(master, vid, exclude_urls))}"
    )


async def _verify_reads(front, blobs):
    for fid, data in blobs.items():
        status, body = await fetch(f"http://{front.url}/{fid}")
        assert status == 200, fid
        assert body == data, f"read of {fid} not byte-exact"


def test_kill_volume_server_autonomous_reconvergence(tmp_path):
    """SIGKILL a shard holder mid-operation: the scheduler must rebuild
    its shards onto the survivors without an operator, and every read
    stays byte-verified before, during, and after."""

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=4, pulse_seconds=1,
            ec_backend="native",
            master_kwargs=dict(ec_repair=RepairConfig(
                interval_seconds=0.25, backoff_base_seconds=0.2,
            )),
        )
        await cluster.start()
        try:
            vid, blobs = await _fill_one_volume(cluster)
            front = await _encode_and_spread(cluster, vid)
            await asyncio.sleep(1.5)  # heartbeat deltas reach the master
            assert len(_held_sids(cluster.master, vid)) == TOTAL_SHARDS

            chaos = ChaosInjector(cluster)
            victim_idx = next(
                i for i, vs in enumerate(cluster.volume_servers)
                if vs is not front
            )
            victim_url = cluster.volume_servers[victim_idx].url
            await chaos.kill_volume_server(victim_idx)
            await asyncio.sleep(0.3)
            front._ec_locations.clear()
            # degraded but recoverable (the victim held < 4 shards)
            assert len(_held_sids(cluster.master, vid)) >= 10

            # the repair plane converges on its own
            await _wait_full_redundancy(
                cluster.master, vid, exclude_urls=(victim_url,)
            )
            sched = cluster.master.repair
            assert sched.totals["completed"] >= 1
            front._ec_locations.clear()
            await _verify_reads(front, blobs)

            # convergence is measured and visible on the status plane
            deadline = time.monotonic() + 10
            while (
                sched.last_time_to_healthy_s is None
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.2)
            st = sched.status()
            assert st["last_time_to_healthy_s"] is not None
            assert st["totals"]["completed"] >= 1

            # health.json carries the repair block
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{cluster.master.url}/cluster/health.json"
                ) as r:
                    assert r.status == 200
                    doc = await r.json()
            assert doc["repair"]["enabled"]
            assert doc["repair"]["totals"]["completed"] >= 1
        finally:
            await cluster.stop()

    run(go())


def test_corrupt_shard_scrub_verdict_repair(tmp_path):
    """Bit-rot a parity shard on disk: the master's scrub sweep must
    localize it, drop the bad copy before rebuilding, and return the
    volume to full redundancy — reads byte-verified after."""

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=2, pulse_seconds=1,
            ec_backend="native",
            master_kwargs=dict(ec_repair=RepairConfig(
                interval_seconds=0.25, scrub_interval_seconds=0.5,
                backoff_base_seconds=0.2,
            )),
        )
        await cluster.start()
        try:
            vid, blobs = await _fill_one_volume(cluster, n_blobs=6)
            # keep all 14 shards on the holder: scrub needs a full set
            front = await _encode_and_spread(cluster, vid, spread=False)
            await asyncio.sleep(1.5)
            holder_idx = cluster.volume_servers.index(front)

            chaos = ChaosInjector(cluster)
            chaos.corrupt_shard(holder_idx, vid, shard_id=11)

            # scrub verdict -> corrupt drop -> rebuild -> full redundancy
            sched = cluster.master.repair
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if sched.totals["completed"] >= 1:
                    break
                await asyncio.sleep(0.2)
            assert sched.totals["completed"] >= 1, sched.status()
            await _wait_full_redundancy(cluster.master, vid)
            # the repaired copy lives somewhere, and reads are byte-exact
            front._ec_locations.clear()
            await _verify_reads(front, blobs)
            # the scrub sweep may transiently re-queue the volume while
            # the post-repair census settles; wait for the steady state
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                v = sched.status()["volumes"][str(vid)]
                if v["state"] in ("repaired", "healthy"):
                    break
                await asyncio.sleep(0.2)
            assert v["state"] in ("repaired", "healthy"), v
        finally:
            await cluster.stop()

    run(go())


def test_heartbeat_partition_stale_node_repair(tmp_path):
    """Partition a holder's heartbeats (stream alive, pulses stopped):
    the master flags it STALE and the scheduler re-establishes its
    shards on fresh nodes WITHOUT gathering from the suspect."""

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=3, pulse_seconds=1,
            ec_backend="native",
            master_kwargs=dict(ec_repair=RepairConfig(
                interval_seconds=0.25, backoff_base_seconds=0.2,
            )),
        )
        await cluster.start()
        try:
            vid, blobs = await _fill_one_volume(cluster, n_blobs=6)
            front = await _encode_and_spread(cluster, vid)
            await asyncio.sleep(1.5)
            chaos = ChaosInjector(cluster)
            victim_idx = next(
                i for i, vs in enumerate(cluster.volume_servers)
                if vs is not front
            )
            victim = cluster.volume_servers[victim_idx]
            chaos.partition_heartbeats(victim_idx)
            # staleness window = 2 pulse intervals
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if victim.url in cluster.master.telemetry.stale_node_urls():
                    break
                await asyncio.sleep(0.2)
            assert victim.url in cluster.master.telemetry.stale_node_urls()

            # every shard ends up held by at least one FRESH node
            await _wait_full_redundancy(
                cluster.master, vid, exclude_urls=(victim.url,)
            )
            assert cluster.master.repair.totals["completed"] >= 1
            chaos.partition_heartbeats(victim_idx, partitioned=False)
            front._ec_locations.clear()
            await _verify_reads(front, blobs)
        finally:
            await cluster.stop()

    run(go())


def test_repair_shell_commands(tmp_path):
    """volume.repair.status / pause / resume against a live master."""
    from seaweedfs_tpu.shell import CommandEnv, run_command

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, pulse_seconds=1,
            master_kwargs=dict(ec_repair=RepairConfig(
                interval_seconds=0.25,
            )),
        )
        await cluster.start()
        try:
            out = io.StringIO()
            env = CommandEnv([cluster.master.advertise_url], out=out)
            await run_command(env, "volume.repair.pause")
            assert cluster.master.repair.paused
            await run_command(env, "volume.repair.status")
            text = out.getvalue()
            assert "PAUSED" in text
            await run_command(env, "volume.repair.resume")
            assert not cluster.master.repair.paused
            out.truncate(0)
            out.seek(0)
            await run_command(env, "volume.repair.status -json")
            import json

            doc = json.loads(out.getvalue())
            assert doc["enabled"] and not doc["paused"]
            assert "totals" in doc and "queue_depth" in doc
        finally:
            await cluster.stop()

    run(go())


def test_breaker_open_defers_repair_cycle(tmp_path):
    """With a volume degraded AND a fresh node reporting an open
    interactive breaker, the scheduler defers instead of repairing —
    the measurable 'repair never competes with the front door'."""

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=3, pulse_seconds=1,
            ec_backend="native",
            master_kwargs=dict(ec_repair=RepairConfig(
                interval_seconds=0.25, breaker_pause_seconds=1.0,
                backoff_base_seconds=0.2,
            )),
        )
        await cluster.start()
        try:
            vid, blobs = await _fill_one_volume(cluster, n_blobs=6)
            front = await _encode_and_spread(cluster, vid)
            await asyncio.sleep(1.5)

            # force the front door's interactive breaker OPEN before
            # the fault, so the first repair cycles meet it open
            qos = front.ec_dispatcher.qos
            from seaweedfs_tpu.serving.qos import INTERACTIVE

            br = qos._breakers[INTERACTIVE]
            for _ in range(br.trip_after + 1):
                br.record_rejection()
            br.cooldown_s = 4.0  # hold it open past a few pulses
            await asyncio.sleep(1.5)  # telemetry carries the state
            assert cluster.master.telemetry.breakers_open() >= 1
            # baseline, not assumed 0: a loaded full-suite box can
            # delay heartbeats past the staleness window during spin-up,
            # and the resulting spurious stale-node repair may complete
            # BEFORE the breaker trips — only post-trip launches matter
            completed_before = cluster.master.repair.totals["completed"]

            chaos = ChaosInjector(cluster)
            victim_idx = next(
                i for i, vs in enumerate(cluster.volume_servers)
                if vs is not front
            )
            victim_url = cluster.volume_servers[victim_idx].url
            await chaos.kill_volume_server(victim_idx)

            sched = cluster.master.repair
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if sched.totals["backoff_breaker"] >= 1:
                    break
                await asyncio.sleep(0.1)
            # the shed is measurable: cycles deferred, nothing launched
            # while the breaker was open
            assert sched.totals["backoff_breaker"] >= 1
            assert sched.totals["completed"] == completed_before

            # once the breaker closes, repair proceeds to convergence
            br.record_success()
            await asyncio.sleep(1.5)
            await _wait_full_redundancy(
                cluster.master, vid, timeout=30,
                exclude_urls=(victim_url,),
            )
            assert sched.totals["completed"] >= completed_before + 1
            front._ec_locations.clear()
            await _verify_reads(front, blobs)
        finally:
            await cluster.stop()

    run(go())
