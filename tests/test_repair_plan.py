"""Repair-plane policy units (seaweedfs_tpu/repair): the planner's
priority rules, the scheduler's backoff / breaker-pause / pause-resume
behavior — all without a cluster (fake master + pinned clocks), so the
policies are pinned independently of the chaos e2e."""
import asyncio

import pytest

from seaweedfs_tpu.repair import RepairConfig, RepairScheduler, plan
from seaweedfs_tpu.repair import planner


def _holders(*sids, url="n1:8080"):
    return {sid: url for sid in sids}


# ------------------------------------------------------------------ planner


def test_plan_healthy_volume_produces_no_job():
    result = plan({1: _holders(*range(14))})
    assert result.jobs == [] and result.unrecoverable == []
    assert result.healthy_vids == [1]


def test_plan_critical_volume_jumps_the_queue():
    # vid 1: 12 shards (2 missing); vid 2: exactly 10 left (critical —
    # one more loss is data loss) must sort FIRST despite missing more
    # only by virtue of criticality; vid 3: 13 shards (1 missing)
    result = plan({
        1: _holders(*range(12)),
        2: _holders(*range(10)),
        3: _holders(*range(13)),
    })
    assert [j.vid for j in result.jobs] == [2, 1, 3]
    assert result.jobs[0].critical
    assert result.jobs[0].missing == [10, 11, 12, 13]


def test_plan_most_missing_first_within_noncritical():
    result = plan({
        1: _holders(*range(13)),
        2: _holders(*range(11)),
    })
    assert [j.vid for j in result.jobs] == [2, 1]


def test_plan_corrupt_shard_counts_as_lost():
    # all 14 present but shard 11 corrupt: healthy=13, missing=[11],
    # and the corrupt holder rides the job for the pre-rebuild drop
    result = plan(
        {1: _holders(*range(14))},
        corrupt={1: {11: "n1:8080"}},
    )
    (job,) = result.jobs
    assert job.missing == [11]
    assert job.corrupt == {11: "n1:8080"}
    assert job.reason == "corrupt"
    assert job.healthy == 13


def test_plan_stale_node_shards_count_as_lost():
    shards = {sid: ("stale:1" if sid in (0, 1) else "live:1")
              for sid in range(14)}
    result = plan({1: shards}, stale_nodes={"stale:1"})
    (job,) = result.jobs
    assert job.missing == [0, 1]
    assert job.reason == "stale_node"


def test_plan_unrecoverable_not_queued():
    result = plan({1: _holders(*range(9))})
    assert result.jobs == []
    (dead,) = result.unrecoverable
    assert dead.vid == 1 and dead.healthy == 9


def test_plan_corrupt_can_make_volume_unrecoverable():
    # 10 shards present but one of them corrupt -> 9 healthy
    result = plan(
        {1: _holders(*range(10))}, corrupt={1: {3: "n1:8080"}}
    )
    assert result.jobs == []
    assert [j.vid for j in result.unrecoverable] == [1]


# ---------------------------------------------------------------- scheduler


class _FakeTelemetry:
    def __init__(self):
        self.stale = set()
        self.open_breakers = 0

    def stale_node_urls(self, now=None):
        return set(self.stale)

    def breakers_open(self, now=None):
        return self.open_breakers


class _FakeTopo:
    def __init__(self):
        self.info = {"data_centers": []}

    def to_info(self):
        return self.info

    def data_nodes(self):
        # the r23 pod census: no nodes -> no pod failure domains
        return []


class _FakeMaster:
    def __init__(self):
        self.telemetry = _FakeTelemetry()
        self.topo = _FakeTopo()
        self.is_leader = True


def _topo_info(vid_shards: dict[int, dict[int, str]]):
    """Topology.to_info()-shaped snapshot: one node per distinct url."""
    by_url: dict[str, dict[int, int]] = {}
    for vid, shards in vid_shards.items():
        for sid, url in shards.items():
            by_url.setdefault(url, {}).setdefault(vid, 0)
            by_url[url][vid] |= 1 << sid
    return {
        "data_centers": [{
            "id": "dc1",
            "racks": [{
                "id": "r1",
                "nodes": [
                    {
                        "id": url,
                        "grpc_port": 18080,
                        "volumes": [],
                        "ec_shards": [
                            {"id": vid, "collection": "",
                             "ec_index_bits": bits}
                            for vid, bits in vids.items()
                        ],
                        "max_volume_counts": {"hdd": 8},
                    }
                    for url, vids in sorted(by_url.items())
                ],
            }],
        }]
    }


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_scheduler_breaker_pause_defers_whole_cycle():
    master = _FakeMaster()
    master.topo.info = _topo_info({1: _holders(*range(12))})
    master.telemetry.open_breakers = 1
    sched = RepairScheduler(
        master, RepairConfig(interval_seconds=0, breaker_pause_seconds=5.0)
    )
    before = dict(sched.totals)

    async def go():
        await sched.tick(now=100.0)
        assert sched._inflight == {}  # nothing started under open breaker
        assert sched.totals["backoff_breaker"] == before["backoff_breaker"] + 1
        # still deferred inside the pause window even with breakers closed
        master.telemetry.open_breakers = 0
        await sched.tick(now=104.0)
        assert sched._inflight == {}
        # past the pause window the cycle runs again (job launches)
        await sched.tick(now=105.5)
        assert sched.totals["queued"] == 1
        await sched.stop()

    _run(go())


def test_scheduler_paused_does_nothing():
    master = _FakeMaster()
    master.topo.info = _topo_info({1: _holders(*range(12))})
    sched = RepairScheduler(master, RepairConfig(interval_seconds=0))
    sched.pause()

    async def go():
        await sched.tick(now=0.0)
        assert sched._inflight == {} and sched.totals["queued"] == 0
        sched.resume()
        await sched.tick(now=1.0)
        # resumed: the job launches (and will fail against the fake
        # topology's dead grpc port — irrelevant here; it STARTED)
        assert sched.totals["queued"] == 1
        await sched.stop()

    _run(go())


def test_scheduler_backoff_is_exponential_and_parks(monkeypatch):
    master = _FakeMaster()
    master.topo.info = _topo_info({7: _holders(*range(12))})
    cfg = RepairConfig(
        interval_seconds=0, backoff_base_seconds=1.0,
        backoff_max_seconds=8.0, max_attempts=3,
    )
    sched = RepairScheduler(master, cfg)

    async def boom(env, nodes, job, **kw):
        raise RuntimeError("injected repair failure")

    monkeypatch.setattr(
        "seaweedfs_tpu.repair.scheduler.executor.repair_volume", boom
    )

    fake_now = [1000.0]
    sched.clock = lambda: fake_now[0]

    async def go():
        delays = []
        for attempt in range(1, cfg.max_attempts + 1):
            await sched.tick()
            # the job task runs to completion (failure) on this loop
            for _ in range(10):
                await asyncio.sleep(0)
            assert sched._inflight == {}
            attempts, next_ok = sched._backoff[7]
            assert attempts == attempt
            delays.append(round(next_ok - fake_now[0], 6))
            # a tick BEFORE the backoff expires must not relaunch
            queued = sched.totals["queued"]
            await sched.tick()
            assert sched.totals["queued"] == queued
            if attempt < cfg.max_attempts:
                assert sched.status()["volumes"]["7"]["state"] == "backoff"
            fake_now[0] = next_ok + 0.01  # the backoff elapses
        # exponential: base 1s doubling per attempt (max 8s not reached)
        assert delays == [1.0, 2.0, 4.0]
        assert sched.totals["failed"] == 1
        assert 7 in sched._parked
        st = sched.status()
        assert st["failed"]["7"]
        assert st["totals"]["backoff_retry"] == cfg.max_attempts - 1
        # parked volumes are not retried, and STAY reported as failed
        await sched.tick()
        assert sched.totals["queued"] == cfg.max_attempts
        assert sched.status()["volumes"]["7"]["state"] == "failed"
        await sched.stop()

    _run(go())


def test_scheduler_records_time_to_healthy():
    master = _FakeMaster()
    sched = RepairScheduler(master, RepairConfig(interval_seconds=0))

    async def go():
        # cycle 1: volume degraded -> clock starts (no job can launch
        # against an empty topology? it CAN launch; pause execution by
        # marking it inflight-free via parked)  — use an unrecoverable
        # volume: detected, never executed.
        master.topo.info = _topo_info({9: _holders(*range(8))})
        await sched.tick(now=50.0)
        assert sched._unhealthy_since == 50.0
        assert sched.status()["volumes"]["9"]["state"] == "unrecoverable"
        # cycle 2: shards came back (node rejoined) -> converged
        master.topo.info = _topo_info({9: _holders(*range(14))})
        await sched.tick(now=61.5)
        assert sched._unhealthy_since is None
        assert sched.last_time_to_healthy_s == pytest.approx(11.5)
        st = sched.status()
        assert st["last_time_to_healthy_s"] == pytest.approx(11.5)
        assert st["last_convergence_unix_ms"] is not None
        assert st["volumes"]["9"]["state"] == "healthy"

    _run(go())


def test_scheduler_max_inflight_bound(monkeypatch):
    master = _FakeMaster()
    master.topo.info = _topo_info({
        vid: _holders(*range(12)) for vid in (1, 2, 3, 4)
    })
    sched = RepairScheduler(
        master, RepairConfig(interval_seconds=0, max_inflight=2)
    )
    gate = asyncio.Event()

    async def stall(env, nodes, job, **kw):
        await gate.wait()
        return {"rebuilder": "x", "rebuilt": [], "spread": {},
                "dropped_corrupt": []}

    monkeypatch.setattr(
        "seaweedfs_tpu.repair.scheduler.executor.repair_volume", stall
    )

    async def go():
        await sched.tick(now=0.0)
        assert len(sched._inflight) == 2  # capped below 4 planned jobs
        gate.set()
        for _ in range(20):
            await asyncio.sleep(0)
        assert sched._inflight == {}
        assert sched.totals["completed"] == 2
        await sched.stop()

    _run(go())


def test_report_corrupt_feeds_next_plan():
    master = _FakeMaster()
    master.topo.info = _topo_info({5: _holders(*range(14))})
    sched = RepairScheduler(master, RepairConfig(interval_seconds=0))
    sched.pause()  # observe planning only
    sched.report_corrupt(5, {11: "n1:8080"})

    async def go():
        sched.resume()
        await sched.tick(now=0.0)
        v = sched.status()["volumes"]["5"]
        assert v["corrupt"] == [11]
        assert v["reason"] == "corrupt"
        await sched.stop()

    _run(go())


def test_config_validation():
    with pytest.raises(ValueError):
        RepairConfig(max_inflight=0).validated()
    with pytest.raises(ValueError):
        RepairConfig(backoff_max_seconds=0.1).validated()
    assert RepairConfig().validated().enabled


# ---------------------------------------------------- loadgen fault schedule


def test_load_scenario_fault_events():
    from seaweedfs_tpu.loadgen import LoadScenario

    assert LoadScenario(connections=1, reads=1).fault_events() == []
    sc = LoadScenario(connections=1, reads=1, kill_at=0.5, revive_at=2.0)
    assert sc.fault_events() == [(0.5, "kill"), (2.0, "revive")]
    # kill-and-stay-dead: the case plain churn could not express
    sc = LoadScenario(connections=1, reads=1, kill_at=1.0)
    assert sc.fault_events() == [(1.0, "kill")]
    with pytest.raises(ValueError):
        LoadScenario(connections=1, reads=1, revive_at=1.0).fault_events()
    with pytest.raises(ValueError):
        LoadScenario(
            connections=1, reads=1, kill_at=2.0, revive_at=1.0
        ).fault_events()


def test_slow_disk_fault_injector(tmp_path):
    """The chaos harness's degraded-spindle knob really delays shard
    preads (and 0 restores full speed)."""
    import time as _time

    from seaweedfs_tpu.storage.ec import volume as ec_vol
    from seaweedfs_tpu.storage.ec.encoder import ec_base_name

    base = ec_base_name(str(tmp_path), 9, "")
    with open(base + ".ec00", "wb") as f:
        f.write(b"x" * 1024)
    shard = ec_vol.EcVolumeShard(str(tmp_path), 9, 0)
    try:
        ec_vol.FAULT_READ_DELAY_S = 0.05
        t0 = _time.perf_counter()
        assert shard.read_at(0, 16) == b"x" * 16
        assert _time.perf_counter() - t0 >= 0.05
        ec_vol.FAULT_READ_DELAY_S = 0.0
        t0 = _time.perf_counter()
        shard.read_at(0, 16)
        assert _time.perf_counter() - t0 < 0.05
    finally:
        ec_vol.FAULT_READ_DELAY_S = 0.0
        shard.close()


def test_plan_rescue_saves_volume_below_fresh_quorum():
    """Fewer than 10 FRESH shards but stale copies close the gap: the
    volume is queued (rescue sources ride the job), not written off."""
    shards = {
        sid: ("stale:1" if sid < 6 else "live:1") for sid in range(14)
    }
    result = plan({1: shards}, stale_nodes={"stale:1"})
    (job,) = result.jobs
    assert result.unrecoverable == []
    assert job.healthy == 8 and len(job.rescue) == 6
    assert job.critical
    # truly below quorum even with rescue -> unrecoverable
    few = {sid: ("stale:1" if sid < 2 else "live:1") for sid in range(8)}
    result2 = plan({2: few}, stale_nodes={"stale:1"})
    assert [j.vid for j in result2.unrecoverable] == [2]


def test_planner_sort_is_deterministic():
    a = planner.RepairJob(vid=2, collection="", missing=[1], healthy=13)
    b = planner.RepairJob(vid=1, collection="", missing=[2], healthy=13)
    assert sorted([a, b], key=planner.RepairJob.sort_key)[0].vid == 1
