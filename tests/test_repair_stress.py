"""Lockwatch + viewguard stress for the repair plane: the executor's
shard lifecycle (unmount/delete -> rebuilt re-mount, what a repair job
does to a holder) racing zero-copy batched reads and tier-style device
evict/re-pin cycles — the exact interleaving the chaos harness creates
when `bench_chaos_sweep` repairs a volume WHILE the load sweep reads it.

Invariants under the race (the sanitizers earn their keep on a real
schedule, per ROADMAP item 3):
  * no observed lock acquisition-order cycle across the cache lock /
    pipeline condition / EcVolume shard map (lockwatch);
  * every read that SUCCEEDS is byte-exact against the oracle and its
    exported zero-copy view verifies at release (viewguard); a read
    that loses its shard mid-repair fails a clean CacheMiss /
    KeyError / FileNotFoundError, never stale bytes.

All device work runs on the CPU test mesh (conftest), mirroring
tests/test_lockwatch_stress.py / test_viewguard_stress.py.
"""
import random
import threading
import time

import lockwatch
import viewguard
from seaweedfs_tpu.ops import rs_resident
from seaweedfs_tpu.storage import ec
from seaweedfs_tpu.storage.volume import Volume

VID = 37
MISSING = 4  # destroyed data shard: every read must reconstruct
CYCLED = 12  # parity shard the "repair" thread unmounts/re-mounts


def _make_volume(tmp_path, count=20, seed=19):
    rng = random.Random(seed)
    v = Volume(str(tmp_path), VID)
    blobs = {}
    for i in range(1, count + 1):
        size = rng.choice([120, 1500, 4096, 30_000])
        data = rng.randbytes(size)
        v.write(i, rng.getrandbits(32), data, name=f"f{i}".encode())
        blobs[i] = data
    v.sync()
    return v, blobs


def test_repair_shard_cycle_races_reads_and_tier_swaps(tmp_path):
    v, blobs = _make_volume(tmp_path)
    base = Volume.base_name(v.dir, v.id, v.collection)
    ec.write_ec_files(base, backend="cpu")
    ec.write_sorted_file_from_idx(base)
    v.close()

    errors: list[BaseException] = []
    good_reads = 0
    clean_misses = 0
    repair_cycles = 0
    stop = threading.Event()
    lock = threading.Lock()

    with lockwatch.watch() as w, viewguard.watch() as g:
        ev = ec.EcVolume(str(tmp_path), VID)
        for sid in range(14):
            if sid != MISSING:
                ev.add_shard(sid)
        cache = rs_resident.DeviceShardCache(
            shard_quantum=1 << 20, layout="blockdiag"
        )
        cache.warm_sizes = ()  # CI convention: no AOT grid compile
        ev.load_shards_to_device(cache)
        nids = sorted(blobs)

        def reader(seed: int):
            nonlocal good_reads, clean_misses
            rng = random.Random(seed)
            deadline = time.time() + 20
            mine = 0
            while time.time() < deadline and mine < 8:
                batch = rng.sample(nids, 3)
                try:
                    out = ev.read_needles_batch(
                        batch, backend="cpu", zero_copy=True
                    )
                except (
                    rs_resident.CacheMiss, KeyError, FileNotFoundError
                ):
                    with lock:
                        clean_misses += 1
                    time.sleep(0.01)
                    continue
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                ok = True
                for nid, res in zip(batch, out):
                    if isinstance(
                        res,
                        (rs_resident.CacheMiss, KeyError,
                         FileNotFoundError),
                    ):
                        with lock:
                            clean_misses += 1
                        ok = False
                        continue
                    if isinstance(res, Exception):
                        errors.append(res)
                        return
                    if bytes(res.data) != blobs[nid]:
                        errors.append(
                            AssertionError(f"stale bytes for needle {nid}")
                        )
                        return
                    if isinstance(res.data, memoryview):
                        g.release(res.data)
                if ok:
                    mine += 1
                    with lock:
                        good_reads += 1

        def repairer():
            """The executor's holder-side choreography, in a loop:
            unmount the shard (close its file handle, evict resident
            copy), then 're-mount the rebuilt shard' — the file is the
            rebuilt output in a real repair."""
            nonlocal repair_cycles
            while not stop.is_set():
                try:
                    shard = ev.delete_shard(CYCLED)
                    if shard is not None:
                        shard.close()
                    cache.evict(VID, CYCLED)
                    time.sleep(0.002)
                    ev.add_shard(CYCLED)
                    with open(
                        ev.shards[CYCLED].path, "rb"
                    ) as f:
                        cache.put(
                            VID, CYCLED,
                            memoryview(f.read()),
                        )
                    with lock:
                        repair_cycles += 1
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return

        def tier_swapper():
            """Tier-style pressure: evict + re-pin survivor shards the
            way a demotion/promotion cycle does."""
            i = 0
            sids = [s for s in range(14) if s not in (MISSING, CYCLED)]
            while not stop.is_set():
                sid = sids[i % len(sids)]
                try:
                    with open(ev.shards[sid].path, "rb") as f:
                        cache.put(VID, sid, memoryview(f.read()))
                except KeyError:
                    pass  # shard between unmount and re-mount
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                i += 1

        threads = [
            threading.Thread(target=reader, args=(1,), name="reader1"),
            threading.Thread(target=reader, args=(2,), name="reader2"),
            threading.Thread(target=repairer, name="repairer"),
            threading.Thread(target=tier_swapper, name="tier"),
        ]
        for t in threads:
            t.start()
        threads[0].join()
        threads[1].join()
        stop.set()
        threads[2].join()
        threads[3].join()
        ev.close()

    assert not errors, errors
    assert good_reads > 0, "no read ever succeeded under the race"
    assert repair_cycles > 0, "the repair cycle never ran"
    assert g.exports_total > 0, "no zero-copy views were ever tracked"
    g.assert_clean()
    w.assert_no_cycles()
