"""Async filer-to-filer replication e2e: two independent clusters, a
FilerSync subscribed to A's metadata stream applying to B with chunk
data re-homed into B's volume servers; checkpoint resume; active-active
loop guard via shared signatures; notification spool.

Reference shapes: weed/command/filer_sync.go,
replication/sink/filersink/, notification/ (SendMessage per mutation).
"""
import asyncio
import os

import aiohttp
import pytest

from seaweedfs_tpu.replication import FilerSync
from seaweedfs_tpu.replication.notification import FileQueueNotifier
from seaweedfs_tpu.server.cluster import LocalCluster


def run(coro):
    return asyncio.run(coro)


async def two_clusters(tmp_path, **filer_kwargs):
    a = LocalCluster(base_dir=str(tmp_path / "a"), n_volume_servers=1,
                     with_filer=True, filer_kwargs=filer_kwargs)
    b = LocalCluster(base_dir=str(tmp_path / "b"), n_volume_servers=1,
                     with_filer=True)
    await a.start()
    await b.start()
    return a, b


def fgrpc(cluster):
    return f"{cluster.filer.ip}:{cluster.filer.grpc_port}"


async def put(cluster, path, data, ctype="application/octet-stream"):
    async with aiohttp.ClientSession() as s:
        async with s.put(
            f"http://{cluster.filer.url}{path}", data=data,
            headers={"Content-Type": ctype},
        ) as r:
            assert r.status == 201


async def get(cluster, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://{cluster.filer.url}{path}") as r:
            return r.status, await r.read()


async def wait_until(pred, timeout=10.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if await pred():
            return True
        await asyncio.sleep(0.1)
    return False


def test_one_way_sync(tmp_path):
    async def go():
        a, b = await two_clusters(tmp_path)
        sync = FilerSync(fgrpc(a), fgrpc(b), signature=777)
        try:
            data = os.urandom(300_000)
            await put(a, "/dir/f1.bin", data)
            sync.start()

            async def have_f1():
                st, body = await get(b, "/dir/f1.bin")
                return st == 200 and body == data

            assert await wait_until(have_f1), "f1 did not replicate"
            # chunks were re-homed: B serves even with A's volumes gone
            entry_b = b.filer.filer.find_entry("/dir/f1.bin")
            entry_a = a.filer.filer.find_entry("/dir/f1.bin")
            fids_a = {c.file_id for c in entry_a.chunks}
            assert all(c.file_id not in fids_a for c in entry_b.chunks)
            assert all(c.source_file_id in fids_a for c in entry_b.chunks)

            # live tail: a rename and a delete propagate
            from seaweedfs_tpu.pb import Stub, filer_pb2
            from seaweedfs_tpu.pb.rpc import channel

            stub = Stub(channel(fgrpc(a)), filer_pb2, "SeaweedFiler")
            await stub.AtomicRenameEntry(
                filer_pb2.AtomicRenameEntryRequest(
                    old_directory="/dir", old_name="f1.bin",
                    new_directory="/dir", new_name="f2.bin",
                )
            )

            async def renamed():
                st1, _ = await get(b, "/dir/f1.bin")
                st2, body = await get(b, "/dir/f2.bin")
                return st1 == 404 and st2 == 200 and body == data

            assert await wait_until(renamed), "rename did not replicate"

            await stub.DeleteEntry(
                filer_pb2.DeleteEntryRequest(
                    directory="/dir", name="f2.bin", is_delete_data=True,
                )
            )

            async def deleted():
                st, _ = await get(b, "/dir/f2.bin")
                return st == 404

            assert await wait_until(deleted), "delete did not replicate"

            # checkpoint resume: stop, write while down, restart, catch up
            await sync.stop()
            data2 = b"offline write " * 1000
            await put(a, "/dir/f3.txt", data2, "text/plain")
            sync2 = FilerSync(fgrpc(a), fgrpc(b), signature=777)
            sync2.start()

            async def have_f3():
                st, body = await get(b, "/dir/f3.txt")
                return st == 200 and body == data2

            assert await wait_until(have_f3), "offline write not caught up"
            assert sync2.applied <= 3, (
                f"resume should replay little, applied={sync2.applied}"
            )
            await sync2.stop()
        finally:
            await a.stop()
            await b.stop()

    run(go())


def test_subtree_remap_and_metadata_update_reuse(tmp_path):
    async def go():
        a, b = await two_clusters(tmp_path)
        sync = FilerSync(
            fgrpc(a), fgrpc(b), path_prefix="/data", target_path="/backup",
            signature=99,
        )
        try:
            data = os.urandom(100_000)
            await put(a, "/data/f.bin", data)
            sync.start()

            async def mapped():
                st, body = await get(b, "/backup/f.bin")
                return st == 200 and body == data

            assert await wait_until(mapped), "subtree remap failed"

            # metadata-only update must NOT re-replicate chunk data
            entry_b = b.filer.filer.find_entry("/backup/f.bin")
            fids_before = [c.file_id for c in entry_b.chunks]
            from seaweedfs_tpu.pb import Stub, filer_pb2
            from seaweedfs_tpu.pb.rpc import channel

            stub = Stub(channel(fgrpc(a)), filer_pb2, "SeaweedFiler")
            resp = await stub.LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory="/data", name="f.bin"
                )
            )
            e = filer_pb2.Entry()
            e.CopyFrom(resp.entry)
            e.attributes.file_mode = 0o600
            await stub.UpdateEntry(
                filer_pb2.UpdateEntryRequest(directory="/data", entry=e)
            )

            async def mode_synced():
                try:
                    eb = b.filer.filer.find_entry("/backup/f.bin")
                except Exception:
                    return False
                return (eb.attr.mode & 0o777) == 0o600

            assert await wait_until(mode_synced), "metadata update not synced"
            entry_b2 = b.filer.filer.find_entry("/backup/f.bin")
            assert [c.file_id for c in entry_b2.chunks] == fids_before, (
                "metadata-only update re-replicated chunk data"
            )
        finally:
            await sync.stop()
            await a.stop()
            await b.stop()

    run(go())


def test_active_active_no_loop(tmp_path):
    async def go():
        a, b = await two_clusters(tmp_path)
        sig = 424242
        ab = FilerSync(fgrpc(a), fgrpc(b), signature=sig)
        ba = FilerSync(fgrpc(b), fgrpc(a), signature=sig)
        try:
            ab.start()
            ba.start()
            await put(a, "/x.bin", b"from-a")
            await put(b, "/y.bin", b"from-b")

            async def both():
                s1, d1 = await get(b, "/x.bin")
                s2, d2 = await get(a, "/y.bin")
                return s1 == 200 and d1 == b"from-a" and s2 == 200 and d2 == b"from-b"

            assert await wait_until(both), "bidirectional sync failed"
            # loop guard: the counters settle — the sync'd copies must not
            # bounce back as new events forever
            await asyncio.sleep(1.0)
            a1, b1 = ab.applied, ba.applied
            await asyncio.sleep(1.0)
            assert (ab.applied, ba.applied) == (a1, b1), "events ping-ponging"
        finally:
            await ab.stop()
            await ba.stop()
            await a.stop()
            await b.stop()

    run(go())


def test_notification_spool(tmp_path):
    async def go():
        spool = str(tmp_path / "events.spool")
        notifier = FileQueueNotifier(spool)
        cluster = LocalCluster(
            base_dir=str(tmp_path / "c"), n_volume_servers=1,
            with_filer=True, filer_kwargs=dict(notifier=notifier),
        )
        await cluster.start()
        try:
            await put(cluster, "/n/file.bin", b"notify me")
            events = FileQueueNotifier.read_all(spool)
            keys = [k for k, _ in events]
            assert any(k == "/n/file.bin" for k in keys), keys
            created = [ev for k, ev in events if k == "/n/file.bin"]
            assert created[-1].new_entry.name == "file.bin"
        finally:
            notifier.close()
            await cluster.stop()

    run(go())
