"""RSCodec behavior tests (numpy backend), mirroring the shape of the
reference's erasure_coding tests (ec_test.go: encode then reconstruct from
random shard subsets; reedsolomon round-trip guarantees)."""
import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs import RSCodec


@pytest.fixture(scope="module")
def codec():
    return RSCodec(backend="numpy")


def _rand(k, b, seed=0):
    return np.random.default_rng(seed).integers(0, 256, (k, b)).astype(np.uint8)


def test_encode_shapes(codec):
    data = _rand(10, 1024)
    parity = codec.encode(data)
    assert parity.shape == (4, 1024)
    assert codec.verify(codec.encode_all(data))


def test_zero_data_zero_parity(codec):
    parity = codec.encode(np.zeros((10, 128), dtype=np.uint8))
    assert not parity.any()


def test_linearity(codec):
    a, b = _rand(10, 256, 1), _rand(10, 256, 2)
    pa, pb = codec.encode(a), codec.encode(b)
    assert np.array_equal(codec.encode(a ^ b), pa ^ pb)


def test_reconstruct_each_single_loss(codec):
    data = _rand(10, 512, 3)
    shards = codec.encode_all(data)
    for lost in range(14):
        present = {i: shards[i] for i in range(14) if i != lost}
        got = codec.reconstruct(present)
        assert set(got) == {lost}
        assert np.array_equal(got[lost], shards[lost])


def test_reconstruct_random_quad_losses(codec):
    """Any 4 losses are recoverable — the RS(10,4) contract the reference's
    ec.rebuild depends on (ec_encoder.go:61)."""
    data = _rand(10, 300, 4)
    shards = codec.encode_all(data)
    rng = np.random.default_rng(5)
    for _ in range(20):
        lost = sorted(rng.choice(14, size=4, replace=False).tolist())
        present = {i: shards[i] for i in range(14) if i not in lost}
        got = codec.reconstruct(present)
        for l in lost:
            assert np.array_equal(got[l], shards[l])


def test_reconstruct_data_only(codec):
    """ReconstructData equivalent: ask only for missing data shards, as the
    degraded read path does (store_ec.go:384)."""
    data = _rand(10, 256, 6)
    shards = codec.encode_all(data)
    present = {i: shards[i] for i in range(14) if i not in (0, 7, 12, 13)}
    got = codec.reconstruct(present, wanted=[0, 7])
    assert set(got) == {0, 7}
    assert np.array_equal(got[0], shards[0])
    assert np.array_equal(got[7], shards[7])


def test_too_few_shards_raises(codec):
    data = _rand(10, 64, 7)
    shards = codec.encode_all(data)
    present = {i: shards[i] for i in range(9)}
    with pytest.raises(ValueError):
        codec.reconstruct(present)


def test_known_generator_vector():
    """Pin the generator matrix so accidental field/matrix changes (which
    would silently break byte-compatibility with reference shard files)
    fail loudly."""
    g = gf256.build_matrix(10, 14)
    # A canary: parity of the unit byte-vector e_d equals generator column d.
    codec = RSCodec(backend="numpy")
    for d in range(10):
        data = np.zeros((10, 1), dtype=np.uint8)
        data[d, 0] = 1
        parity = codec.encode(data)
        assert np.array_equal(parity[:, 0], g[10:, d])
