"""Device-resident EC shard cache: batched on-device degraded reads.

Validates ops/rs_resident.py against the numpy oracle and the EcVolume
wiring (resident fast path + read_needles_batch coalescing).  Runs on the
CPU test mesh (Pallas interpret / XLA); the real-TPU latency claim is
measured by bench.py's degraded_p99_ms_device_resident config.
"""
import random

import numpy as np
import pytest

from seaweedfs_tpu.ops import rs, rs_resident
from seaweedfs_tpu.storage import ec

from test_ec import encode_volume, make_volume


@pytest.fixture(scope="module")
def coded():
    rng = np.random.default_rng(42)
    length = 300_000
    codec = rs.RSCodec(backend="numpy")
    data = rng.integers(0, 256, size=(10, length), dtype=np.uint8)
    return codec.encode_all(data)  # [14, length]


def fill_cache(shards, missing=(), vid=7, quantum=1 << 20):
    cache = rs_resident.DeviceShardCache(shard_quantum=quantum)
    for sid in range(shards.shape[0]):
        if sid not in missing:
            cache.put(vid, sid, shards[sid])
    return cache


class TestCache:
    def test_put_get_sizes(self, coded):
        cache = fill_cache(coded, missing=range(4, 14))
        assert cache.shard_ids(7) == [0, 1, 2, 3]
        assert cache.shard_size(7, 0) == coded.shape[1]
        assert cache.get(7, 9) is None
        got = np.asarray(cache.get(7, 2))[: coded.shape[1]]
        np.testing.assert_array_equal(got, coded[2])

    def test_budget_evicts_lru(self, coded):
        one = rs_resident.DeviceShardCache(shard_quantum=1 << 20).\
            _padded_len(coded.shape[1])
        cache = rs_resident.DeviceShardCache(
            budget_bytes=3 * one, shard_quantum=1 << 20
        )
        for sid in range(4):
            cache.put(7, sid, coded[sid])
        assert cache.shard_ids(7) == [1, 2, 3]  # 0 evicted (LRU)
        assert cache.bytes_used == 3 * one
        cache.get(7, 1)  # refresh 1
        cache.put(7, 9, coded[9])
        assert cache.shard_ids(7) == [1, 3, 9]  # 2 was the new LRU

    def test_evict_volume(self, coded):
        cache = fill_cache(coded)
        cache.put(8, 0, coded[0])
        cache.evict(7)
        assert cache.shard_ids(7) == []
        assert cache.shard_ids(8) == [0]
        cache.clear()
        assert cache.bytes_used == 0


class TestReconstruct:
    def test_oracle_mixed_sizes(self, coded):
        cache = fill_cache(coded, missing=(3, 11))
        length = coded.shape[1]
        reqs = [
            (3, 5, 4096),        # unaligned offset
            (11, 131000, 70000),  # parity shard, spans buckets
            (3, 0, 1),
            (11, length - 1000, 1000),  # tail
        ]
        outs = rs_resident.reconstruct_intervals(cache, 7, reqs)
        for (sid, off, size), out in zip(reqs, outs):
            assert out == coded[sid][off : off + size].tobytes()

    def test_oracle_chunk_split(self, coded):
        # larger than the biggest size bucket: must split and reassemble
        big = rs_resident.MAX_TILE + 12345
        rng = np.random.default_rng(1)
        codec = rs.RSCodec(backend="numpy")
        data = rng.integers(0, 256, size=(10, big + 4096), dtype=np.uint8)
        shards = codec.encode_all(data)
        cache = fill_cache(shards, missing=(0,), vid=9, quantum=1 << 22)
        (out,) = rs_resident.reconstruct_intervals(cache, 9, [(0, 17, big)])
        assert out == shards[0][17 : 17 + big].tobytes()

    def test_batch_64(self, coded):
        cache = fill_cache(coded, missing=(3, 11))
        rng = random.Random(2)
        length = coded.shape[1]
        reqs = [
            (rng.choice([3, 11]), rng.randrange(0, length - 4096), 4096)
            for _ in range(64)
        ]
        outs = rs_resident.reconstruct_intervals(cache, 7, reqs)
        for (sid, off, size), out in zip(reqs, outs):
            assert out == coded[sid][off : off + size].tobytes()

    def test_fused_kernel_matches_oracle(self, coded):
        """The fused DMA gather+reconstruct kernel (the real-TPU serving
        path) in pallas interpret mode, against the numpy oracle: mixed
        sizes, unaligned offsets, multi-chunk grids, and a 64-batch."""
        cache = fill_cache(coded, missing=(3, 11))
        length = coded.shape[1]
        rng = random.Random(3)
        reqs = [
            (3, 5, 100),
            (11, 131, 40000),
            (3, length - 1000, 1000),
        ] + [
            (rng.choice([3, 11]), rng.randrange(0, length - 8192), 8192)
            for _ in range(61)
        ]
        outs = rs_resident.reconstruct_intervals(
            cache, 7, reqs, kernel="pallas", interpret=True
        )
        for (sid, off, size), out in zip(reqs, outs):
            assert out == coded[sid][off : off + size].tobytes()

    def test_make_batched_call_shapes(self, coded):
        cache = fill_cache(coded, missing=(3,))
        # offsets FUSED_ALIGN-aligned so the raw device array starts at
        # the requested byte under both the fused and gather paths
        reqs = [(3, 4096 * i, 4096) for i in range(8)]
        for kernel in ("pallas", "xla"):
            thunk = rs_resident.make_batched_call(
                cache, 7, reqs, kernel=kernel, interpret=True
            )
            out = np.asarray(thunk()).reshape(8, -1)  # flat D2H by design
            assert out.shape[1] >= 4096
            for i in range(8):
                assert (
                    out[i, : 4096] == coded[3][4096 * i : 4096 * i + 4096]
                ).all()

    def test_cache_miss(self, coded):
        cache = fill_cache(coded, missing=range(5, 14))
        with pytest.raises(rs_resident.CacheMiss):
            rs_resident.reconstruct_intervals(cache, 7, [(3, 0, 100)])

    def test_empty_requests(self, coded):
        cache = fill_cache(coded)
        assert rs_resident.reconstruct_intervals(cache, 7, []) == []


class TestEcVolumeWiring:
    def test_degraded_read_via_resident(self, tmp_path, monkeypatch):
        v, blobs = make_volume(tmp_path)
        encode_volume(v)
        ev = ec.EcVolume(str(tmp_path), v.id)
        down = {0, 11}  # shard 0 holds needle data in a small volume
        for i in range(14):
            if i not in down:
                ev.add_shard(i)
        cache = rs_resident.DeviceShardCache(shard_quantum=1 << 20)
        assert ev.load_shards_to_device(cache) == 12
        # count resident calls to prove the fast path actually serves
        calls = []
        real = rs_resident.reconstruct_intervals

        def counting(*a, **kw):
            calls.append(a)
            return real(*a, **kw)

        monkeypatch.setattr(rs_resident, "reconstruct_intervals", counting)
        for nid, (cookie, data) in blobs.items():
            assert ev.read_needle(nid, cookie=cookie).data == data
        assert calls, "resident path never used"
        ev.close()

    def test_batch_read_coalesces(self, tmp_path, monkeypatch):
        v, blobs = make_volume(tmp_path, count=16)
        encode_volume(v)
        ev = ec.EcVolume(str(tmp_path), v.id)
        down = {0, 7}
        for i in range(14):
            if i not in down:
                ev.add_shard(i)
        cache = rs_resident.DeviceShardCache(shard_quantum=1 << 20)
        ev.load_shards_to_device(cache)
        calls = []
        real = rs_resident.reconstruct_intervals

        def counting(*a, **kw):
            calls.append(a[2])
            return real(*a, **kw)

        monkeypatch.setattr(rs_resident, "reconstruct_intervals", counting)
        nids = list(blobs)
        needles = ev.read_needles_batch(nids)
        for nid, n in zip(nids, needles):
            cookie, data = blobs[nid]
            assert n.data == data and n.cookie == cookie
        # every missing-shard interval went through ONE coalesced call
        assert len(calls) == 1 and len(calls[0]) >= 2
        ev.close()

    def test_batch_read_isolates_bad_ids(self, tmp_path):
        v, blobs = make_volume(tmp_path, count=6)
        encode_volume(v)
        ev = ec.EcVolume(str(tmp_path), v.id)
        for i in range(14):
            ev.add_shard(i)
        nids = list(blobs)
        mixed = [nids[0], 0xDEAD_BEEF, nids[1]]  # middle id doesn't exist
        results = ev.read_needles_batch(mixed)
        assert results[0].data == blobs[nids[0]][1]
        assert isinstance(results[1], ec.volume.NeedleNotFound)
        assert results[2].data == blobs[nids[1]][1]
        ev.close()

    def test_batch_read_without_cache_falls_back(self, tmp_path):
        v, blobs = make_volume(tmp_path, count=6)
        encode_volume(v)
        ev = ec.EcVolume(str(tmp_path), v.id)
        for i in range(14):
            if i not in (2,):
                ev.add_shard(i)
        nids = list(blobs)
        needles = ev.read_needles_batch(nids)
        for nid, n in zip(nids, needles):
            assert n.data == blobs[nid][1]
        ev.close()

    def test_server_dispatcher_coalesces(self, tmp_path):
        """EcReadDispatcher: concurrent reads of a resident volume land
        in one Store.read_ec_needles_batch call; failures stay
        per-needle.  (The dispatcher's own unit suite is
        tests/test_serving_dispatcher.py — this keeps the resident-path
        contract pinned next to the cache tests.)"""
        import asyncio

        from seaweedfs_tpu.serving import EcReadDispatcher, ServingConfig

        calls = []

        class FakeStore:
            def ec_volume_is_resident(self, vid):
                return True

            def read_ec_needles_batch(
                self, vid, requests, remote_read=None, zero_copy=False
            ):
                calls.append(list(requests))
                out = []
                for nid, _cookie in requests:
                    if nid == 99:
                        out.append(KeyError("nope"))
                    else:
                        out.append(f"needle-{vid}-{nid}")
                return out

        async def go():
            b = EcReadDispatcher(
                FakeStore(), lambda vid: None,
                ServingConfig(max_inflight=1, max_wait_us=0),
            )

            # first read starts a drain; the rest arrive while it runs
            # and must coalesce into ONE follow-up batch
            results = await asyncio.gather(
                b.read(1, 1, None),
                b.read(1, 2, None),
                b.read(1, 3, None),
                b.read(1, 99, None),
                return_exceptions=True,
            )
            assert results[0] == "needle-1-1"
            assert results[1] == "needle-1-2"
            assert results[2] == "needle-1-3"
            assert isinstance(results[3], KeyError)
            assert len(calls) <= 2  # 1 leading + 1 coalesced batch
            total = sum(len(c) for c in calls)
            assert total == 4

        asyncio.run(go())

    def test_eviction_on_shard_delete(self, tmp_path):
        v, _ = make_volume(tmp_path, count=4)
        encode_volume(v)
        ev = ec.EcVolume(str(tmp_path), v.id)
        for i in range(14):
            ev.add_shard(i)
        cache = rs_resident.DeviceShardCache(shard_quantum=1 << 20)
        ev.load_shards_to_device(cache)
        assert len(cache.shard_ids(v.id)) == 14
        ev.delete_shard(5)
        assert 5 not in cache.shard_ids(v.id)
        ev.destroy()
        assert cache.shard_ids(v.id) == []
