"""TPU backend parity tests: xla and pallas (interpret-mode on the CPU test
mesh) must match the numpy oracle bit-for-bit — the same test shape the
reference uses for its EC layer (encode then reconstruct from random shard
subsets, ec_test.go)."""
import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256, rs_cpu, rs_tpu
from seaweedfs_tpu.ops.rs import RSCodec


def _rand(k, b, seed=0):
    return np.random.default_rng(seed).integers(0, 256, (k, b)).astype(np.uint8)


@pytest.mark.parametrize("kernel", ["xla", "pallas"])
def test_apply_matrix_matches_numpy(kernel):
    m = gf256.parity_matrix(10, 14)
    x = _rand(10, 1000, 1)  # deliberately not a tile multiple
    want = rs_cpu.apply_matrix_numpy(m, x)
    got = rs_tpu.apply_matrix(m, x, kernel=kernel)
    assert np.array_equal(got, want)


@pytest.mark.parametrize("kernel", ["xla", "pallas"])
def test_arbitrary_matrix_rows(kernel):
    """Reconstruction matrices have 1..4 rows; row padding must slice off."""
    rng = np.random.default_rng(2)
    for rows in (1, 2, 3, 4, 5, 14):
        m = rng.integers(0, 256, (rows, 10)).astype(np.uint8)
        x = _rand(10, 256, rows)
        assert np.array_equal(
            rs_tpu.apply_matrix(m, x, kernel=kernel),
            rs_cpu.apply_matrix_numpy(m, x),
        )


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_codec_roundtrip(backend):
    codec = RSCodec(backend=backend)
    data = _rand(10, 5000, 3)
    shards = codec.encode_all(data)
    assert codec.verify(shards)
    # 4 losses incl. parity
    lost = [0, 5, 11, 13]
    present = {i: shards[i] for i in range(14) if i not in lost}
    got = codec.reconstruct(present)
    for l in lost:
        assert np.array_equal(got[l], shards[l])


def test_cross_backend_identical():
    """numpy, xla, pallas parity bytes are identical -> shard files written
    by any backend are interchangeable."""
    data = _rand(10, 4096, 4)
    outs = [RSCodec(backend=b).encode(data) for b in ("numpy", "xla", "pallas")]
    assert np.array_equal(outs[0], outs[1])
    assert np.array_equal(outs[0], outs[2])


def test_large_batch_tiling():
    """B spanning multiple grid tiles incl. a ragged tail (explicit small
    tile so interpret mode stays fast; the real-TPU multi-tile path is
    exercised by bench.py on hardware)."""
    m = gf256.parity_matrix(10, 14)
    x = _rand(10, 3 * 512 + 77, 5)
    assert np.array_equal(
        rs_tpu.apply_matrix(m, x, kernel="pallas", tile=512),
        rs_cpu.apply_matrix_numpy(m, x),
    )


def test_blockdiag_matches_numpy():
    """Block-diagonal fast path (segment-stacked host staging, ~152 GB/s
    on v5e) is bit-identical to the plain formulation."""
    m = gf256.parity_matrix(10, 14)
    for b in (4 * 512, 4 * 512 + 4):  # divisible by groups; uneven tile
        x = _rand(10, b, 6)
        got = rs_tpu.apply_matrix_blockdiag(m, x, tile=512)
        assert np.array_equal(got, rs_cpu.apply_matrix_numpy(m, x))


def test_blockdiag_reconstruction_matrix():
    """Rebuild matrices (arbitrary rows/cols) ride the same path."""
    rmat, use = gf256.reconstruction_matrix(
        10, 14, [i for i in range(14) if i not in (1, 4, 10, 12)],
        [1, 4, 10, 12],
    )
    codec = RSCodec(backend="numpy")
    data = _rand(10, 4 * 1024, 7)
    shards = codec.encode_all(data)
    got = rs_tpu.apply_matrix_blockdiag(rmat, shards[use], tile=1024)
    assert np.array_equal(got, shards[[1, 4, 10, 12]])


def test_blockdiag_indivisible_falls_back():
    m = gf256.parity_matrix(10, 14)
    x = _rand(10, 4 * 512 + 3, 8)  # not divisible by groups
    got = rs_tpu.apply_matrix_blockdiag(m, x, tile=512)
    assert np.array_equal(got, rs_cpu.apply_matrix_numpy(m, x))


def test_stack_unstack_inverse():
    x = _rand(10, 4 * 333, 9)
    st = rs_tpu.stack_segments(x)
    assert st.shape == (40, 333)
    # parity-shaped output round-trip (m_pad rows per group)
    out = _rand(16, 333, 10)
    flat = rs_tpu.unstack_segments(out, 4)
    assert flat.shape == (4, 4 * 333)
    for g in range(4):
        assert np.array_equal(flat[:, g * 333 : (g + 1) * 333], out[g * 4 : g * 4 + 4])
