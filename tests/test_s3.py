"""S3 gateway e2e: full round-trip (create bucket, put/get/range
get/list/delete, multipart upload, SigV4 auth) against an in-process
cluster.  The client side signs requests with an independent SigV4
implementation (s3api.auth.sign_request_headers), standing in for the
reference's AWS-SDK-based tests (test/s3/basic) since boto3 isn't in the
image."""
import asyncio
import hashlib
import os
import xml.etree.ElementTree as ET

import aiohttp
import pytest

from seaweedfs_tpu.s3api import Identity, IdentityAccessManagement, sign_request_headers
from seaweedfs_tpu.s3api.auth import _canonical_query  # noqa: F401 (sanity import)
from seaweedfs_tpu.server.cluster import LocalCluster

ACCESS, SECRET = "AKIDEXAMPLE", "sekrit123"


def run(coro):
    return asyncio.run(coro)


async def make_cluster(tmp_path, auth=False):
    iam = None
    if auth:
        iam = IdentityAccessManagement(
            [Identity(name="admin", credentials=[(ACCESS, SECRET)], actions=["Admin"])]
        )
    cluster = LocalCluster(
        base_dir=str(tmp_path), n_volume_servers=2, with_s3=True,
        s3_kwargs=dict(iam=iam) if iam else {},
    )
    await cluster.start()
    return cluster


class S3Client:
    """Minimal signing S3 client for tests."""

    def __init__(self, endpoint: str, access: str = "", secret: str = ""):
        self.endpoint = endpoint
        self.access = access
        self.secret = secret

    async def request(self, method, path, data=b"", headers=None, query=""):
        url = f"http://{self.endpoint}{path}"
        if query:
            url += f"?{query}"
        headers = dict(headers or {})
        if self.access:
            headers = sign_request_headers(
                method, url, headers, data, self.access, self.secret
            )
        async with aiohttp.ClientSession() as s:
            async with s.request(method, url, data=data, headers=headers) as r:
                return r.status, await r.read(), r.headers.copy()  # case-insensitive


def _xml(body):
    return ET.fromstring(body)


def _strip(tag):
    return tag.split("}")[-1]


def test_s3_basic_round_trip(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        c = S3Client(cluster.s3.url)
        try:
            # create bucket
            status, _, _ = await c.request("PUT", "/mybucket")
            assert status == 200
            # duplicate rejected
            status, body, _ = await c.request("PUT", "/mybucket")
            assert status == 409
            # list buckets
            status, body, _ = await c.request("GET", "/")
            assert b"mybucket" in body

            # put / get
            payload = os.urandom(300000)
            status, _, hdrs = await c.request("PUT", "/mybucket/dir/obj1.bin", payload)
            assert status == 200
            assert hdrs["ETag"] == f'"{hashlib.md5(payload).hexdigest()}"'
            status, body, hdrs = await c.request("GET", "/mybucket/dir/obj1.bin")
            assert status == 200 and body == payload
            # range get
            status, body, _ = await c.request(
                "GET", "/mybucket/dir/obj1.bin", headers={"Range": "bytes=100-199"}
            )
            assert status == 206 and body == payload[100:200]
            # head
            status, body, hdrs = await c.request("HEAD", "/mybucket/dir/obj1.bin")
            assert status == 200 and hdrs["Content-Length"] == str(len(payload))
            # missing key
            status, _, _ = await c.request("GET", "/mybucket/nope")
            assert status == 404

            # more objects for listing
            for name in ["a.txt", "dir/obj2.bin", "zed/x", "zed/y"]:
                await c.request("PUT", f"/mybucket/{name}", b"data-" + name.encode())

            # flat list
            status, body, _ = await c.request("GET", "/mybucket")
            keys = [
                e.findtext("{%s}Key" % "http://s3.amazonaws.com/doc/2006-03-01/")
                for e in _xml(body)
                if _strip(e.tag) == "Contents"
            ]
            assert keys == ["a.txt", "dir/obj1.bin", "dir/obj2.bin", "zed/x", "zed/y"]

            # delimiter list
            status, body, _ = await c.request("GET", "/mybucket", query="delimiter=%2F")
            doc = _xml(body)
            ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
            keys = [e.findtext(f"{ns}Key") for e in doc if _strip(e.tag) == "Contents"]
            cps = [
                e.findtext(f"{ns}Prefix")
                for e in doc
                if _strip(e.tag) == "CommonPrefixes"
            ]
            assert keys == ["a.txt"] and cps == ["dir/", "zed/"]

            # prefix + delimiter
            status, body, _ = await c.request(
                "GET", "/mybucket", query="prefix=dir%2F&delimiter=%2F"
            )
            doc = _xml(body)
            keys = [e.findtext(f"{ns}Key") for e in doc if _strip(e.tag) == "Contents"]
            assert keys == ["dir/obj1.bin", "dir/obj2.bin"]

            # pagination (max-keys + continuation)
            status, body, _ = await c.request(
                "GET", "/mybucket", query="list-type=2&max-keys=2"
            )
            doc = _xml(body)
            keys = [e.findtext(f"{ns}Key") for e in doc if _strip(e.tag) == "Contents"]
            token = doc.findtext(f"{ns}NextContinuationToken")
            assert keys == ["a.txt", "dir/obj1.bin"]
            assert doc.findtext(f"{ns}IsTruncated") == "true"
            status, body, _ = await c.request(
                "GET", "/mybucket",
                query=f"list-type=2&max-keys=10&continuation-token={token}",
            )
            doc = _xml(body)
            keys = [e.findtext(f"{ns}Key") for e in doc if _strip(e.tag) == "Contents"]
            assert keys == ["dir/obj2.bin", "zed/x", "zed/y"]

            # copy
            status, body, _ = await c.request(
                "PUT", "/mybucket/copy.bin",
                headers={"x-amz-copy-source": "/mybucket/dir/obj1.bin"},
            )
            assert status == 200
            status, body, _ = await c.request("GET", "/mybucket/copy.bin")
            assert body == payload

            # delete multiple
            delete_xml = (
                b"<Delete>"
                b"<Object><Key>zed/x</Key></Object>"
                b"<Object><Key>zed/y</Key></Object>"
                b"</Delete>"
            )
            status, body, _ = await c.request(
                "POST", "/mybucket", data=delete_xml, query="delete="
            )
            assert status == 200 and body.count(b"<Deleted>") == 2

            # single delete + 404 after
            status, _, _ = await c.request("DELETE", "/mybucket/a.txt")
            assert status == 204
            status, _, _ = await c.request("GET", "/mybucket/a.txt")
            assert status == 404

            # bucket not empty
            status, _, _ = await c.request("DELETE", "/mybucket")
            assert status == 409
            for k in ["dir/obj1.bin", "dir/obj2.bin", "copy.bin"]:
                await c.request("DELETE", f"/mybucket/{k}")
            status, _, _ = await c.request("DELETE", "/mybucket")
            assert status == 204
            status, _, _ = await c.request("HEAD", "/mybucket")
            assert status == 404
        finally:
            await cluster.stop()

    run(go())


def test_s3_multipart_upload(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        c = S3Client(cluster.s3.url)
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        try:
            await c.request("PUT", "/mp")
            status, body, _ = await c.request(
                "POST", "/mp/big/file.bin", query="uploads="
            )
            assert status == 200
            upload_id = _xml(body).findtext(f"{ns}UploadId")
            assert upload_id

            parts = [os.urandom(5 * 1024 * 1024), os.urandom(5 * 1024 * 1024), os.urandom(1234)]
            etags = []
            for i, data in enumerate(parts, start=1):
                status, _, hdrs = await c.request(
                    "PUT", "/mp/big/file.bin", data,
                    query=f"partNumber={i}&uploadId={upload_id}",
                )
                assert status == 200
                assert hdrs["ETag"] == f'"{hashlib.md5(data).hexdigest()}"'
                etags.append(hdrs["ETag"])

            # list parts
            status, body, _ = await c.request(
                "GET", "/mp/big/file.bin", query=f"uploadId={upload_id}"
            )
            doc = _xml(body)
            nums = [
                int(p.findtext(f"{ns}PartNumber"))
                for p in doc
                if _strip(p.tag) == "Part"
            ]
            assert nums == [1, 2, 3]

            complete = "<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{i}</PartNumber><ETag>{e}</ETag></Part>"
                for i, e in enumerate(etags, start=1)
            ) + "</CompleteMultipartUpload>"
            status, body, _ = await c.request(
                "POST", "/mp/big/file.bin", complete.encode(),
                query=f"uploadId={upload_id}",
            )
            assert status == 200
            etag = _xml(body).findtext(f"{ns}ETag")
            want = hashlib.md5(
                b"".join(hashlib.md5(p).digest() for p in parts)
            ).hexdigest()
            assert etag == f'"{want}-3"'

            full = b"".join(parts)
            status, body, hdrs = await c.request("GET", "/mp/big/file.bin")
            assert status == 200 and body == full
            assert hdrs["ETag"] == f'"{want}-3"'
            # ranged read across part boundary
            status, body, _ = await c.request(
                "GET", "/mp/big/file.bin",
                headers={"Range": f"bytes={5 * 1024 * 1024 - 100}-{5 * 1024 * 1024 + 99}"},
            )
            assert body == full[5 * 1024 * 1024 - 100 : 5 * 1024 * 1024 + 100]

            # staging dir is gone
            status, body, _ = await c.request("GET", "/mp", query="uploads=")
            assert body.count(b"<Upload>") == 0

            # abort flow
            status, body, _ = await c.request("POST", "/mp/tmp.bin", query="uploads=")
            uid2 = _xml(body).findtext(f"{ns}UploadId")
            await c.request(
                "PUT", "/mp/tmp.bin", b"x" * 1000, query=f"partNumber=1&uploadId={uid2}"
            )
            status, _, _ = await c.request(
                "DELETE", "/mp/tmp.bin", query=f"uploadId={uid2}"
            )
            assert status == 204
            status, body, _ = await c.request(
                "GET", "/mp/tmp.bin", query=f"uploadId={uid2}"
            )
            assert status == 404
        finally:
            await cluster.stop()

    run(go())


def test_s3_sigv4_auth(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path, auth=True)
        good = S3Client(cluster.s3.url, ACCESS, SECRET)
        bad_key = S3Client(cluster.s3.url, "AKIDWRONG", SECRET)
        bad_secret = S3Client(cluster.s3.url, ACCESS, "wrong")
        anon = S3Client(cluster.s3.url)
        try:
            status, _, _ = await good.request("PUT", "/auth-bucket")
            assert status == 200
            status, _, _ = await good.request("PUT", "/auth-bucket/f", b"hello")
            assert status == 200

            status, body, _ = await anon.request("GET", "/auth-bucket/f")
            assert status == 403 and b"AccessDenied" in body
            status, body, _ = await bad_key.request("GET", "/auth-bucket/f")
            assert status == 403 and b"InvalidAccessKeyId" in body
            status, body, _ = await bad_secret.request("GET", "/auth-bucket/f")
            assert status == 403 and b"SignatureDoesNotMatch" in body

            status, body, _ = await good.request("GET", "/auth-bucket/f")
            assert status == 200 and body == b"hello"

            # signing covers the query string too
            status, body, _ = await good.request(
                "GET", "/auth-bucket", query="list-type=2&prefix=f"
            )
            assert status == 200 and b"<Key>f</Key>" in body
        finally:
            await cluster.stop()

    run(go())


def test_s3_review_regressions(tmp_path):
    """Round-2 code-review findings: prefix-delete no-op, traversal
    rejection, write-action bulk delete, dir markers, copy metadata,
    aws-chunked decode."""

    async def go():
        cluster = await make_cluster(tmp_path)
        c = S3Client(cluster.s3.url)
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        try:
            await c.request("PUT", "/rb")
            await c.request("PUT", "/rb/a/b", b"B")
            await c.request("PUT", "/rb/a/c", b"C")

            # DELETE of a key matching a prefix directory must be a no-op
            status, _, _ = await c.request("DELETE", "/rb/a")
            assert status == 204
            status, body, _ = await c.request("GET", "/rb/a/b")
            assert status == 200 and body == b"B"  # subtree survived

            # path traversal rejected (raw socket: clients normalize '..'
            # before sending, attackers don't)
            for raw_path in ("/rb/../evil", "/rb/a/../c", "/rb/%2e%2e/evil"):
                reader, writer = await asyncio.open_connection(
                    cluster.s3.ip, cluster.s3.port
                )
                writer.write(
                    f"PUT {raw_path} HTTP/1.1\r\nHost: x\r\n"
                    "Content-Length: 1\r\n\r\nz".encode()
                )
                await writer.drain()
                status_line = await reader.readline()
                assert b"400" in status_line, (raw_path, status_line)
                writer.close()

            # directory marker keys
            status, _, _ = await c.request("PUT", "/rb/folder/", b"")
            assert status == 200
            status, _, _ = await c.request("PUT", "/rb/folder/inner.txt", b"in")
            assert status == 200  # prefix not shadowed by a file
            status, body, _ = await c.request("GET", "/rb/folder/inner.txt")
            assert body == b"in"
            await c.request("DELETE", "/rb/folder/inner.txt")
            status, _, _ = await c.request("DELETE", "/rb/folder/")
            assert status == 204

            # copy preserves content-type + metadata
            await c.request(
                "PUT", "/rb/src.json", b"{}",
                headers={"Content-Type": "application/json", "X-Amz-Meta-K": "v"},
            )
            await c.request(
                "PUT", "/rb/dst.json",
                headers={"x-amz-copy-source": "/rb/src.json"},
            )
            status, _, hdrs = await c.request("GET", "/rb/dst.json")
            assert hdrs["Content-Type"] == "application/json"
            assert hdrs.get("x-amz-meta-k") == "v"

            # aws-chunked framing is stripped
            payload = b"hello-chunked-world" * 100
            framed = (
                f"{len(payload):x};chunk-signature=deadbeef\r\n".encode()
                + payload
                + b"\r\n0;chunk-signature=deadbeef\r\n\r\n"
            )
            status, _, _ = await c.request(
                "PUT", "/rb/chunked.bin", framed,
                headers={
                    "x-amz-content-sha256": "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
                    "Content-Encoding": "aws-chunked",
                },
            )
            assert status == 200
            status, body, _ = await c.request("GET", "/rb/chunked.bin")
            assert body == payload
        finally:
            await cluster.stop()

    run(go())


def test_s3_readonly_identity_cannot_bulk_delete(tmp_path):
    async def go():
        iam = IdentityAccessManagement(
            [
                Identity(name="admin", credentials=[(ACCESS, SECRET)], actions=["Admin"]),
                Identity(
                    name="reader",
                    credentials=[("AKIDREAD", "readsecret")],
                    actions=["Read", "List"],
                ),
            ]
        )
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, with_s3=True,
            s3_kwargs=dict(iam=iam),
        )
        await cluster.start()
        admin = S3Client(cluster.s3.url, ACCESS, SECRET)
        reader = S3Client(cluster.s3.url, "AKIDREAD", "readsecret")
        try:
            await admin.request("PUT", "/guard")
            await admin.request("PUT", "/guard/keep", b"data")
            delete_xml = b"<Delete><Object><Key>keep</Key></Object></Delete>"
            status, body, _ = await reader.request(
                "POST", "/guard", data=delete_xml, query="delete="
            )
            assert status == 403
            status, body, _ = await reader.request("GET", "/guard/keep")
            assert status == 200 and body == b"data"
            # plain object delete also denied for the reader
            status, _, _ = await reader.request("DELETE", "/guard/keep")
            assert status == 403
        finally:
            await cluster.stop()

    run(go())


def test_s3_tagging_and_metadata(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        c = S3Client(cluster.s3.url)
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        try:
            await c.request("PUT", "/tb")
            status, _, _ = await c.request(
                "PUT", "/tb/o", b"data",
                headers={
                    "X-Amz-Tagging": "env=prod&team=storage",
                    "X-Amz-Meta-Owner": "me",
                },
            )
            assert status == 200
            status, body, _ = await c.request("GET", "/tb/o", query="tagging=")
            doc = _xml(body)
            tags = {
                t.findtext(f"{ns}Key"): t.findtext(f"{ns}Value")
                for t in doc.iter(f"{ns}Tag")
            }
            assert tags == {"env": "prod", "team": "storage"}
            status, _, hdrs = await c.request("GET", "/tb/o")
            assert hdrs.get("x-amz-meta-owner") == "me"
            # replace tags
            new = b"<Tagging><TagSet><Tag><Key>only</Key><Value>one</Value></Tag></TagSet></Tagging>"
            status, _, _ = await c.request("PUT", "/tb/o", new, query="tagging=")
            assert status == 200
            status, body, _ = await c.request("GET", "/tb/o", query="tagging=")
            assert b"only" in body and b"env" not in body
            status, _, _ = await c.request("DELETE", "/tb/o", query="tagging=")
            assert status == 204
            status, body, _ = await c.request("GET", "/tb/o", query="tagging=")
            assert b"<Tag>" not in body
        finally:
            await cluster.stop()

    run(go())


def test_s3_request_payment_and_signed_response_overrides(tmp_path):
    """GetBucketRequestPayment returns the BucketOwner payer document
    (reference s3api_bucket_handlers.go:352-360); response-* GetObject
    overrides are honored only on SIGNED requests when auth is enabled —
    AWS rejects them on anonymous reads with 400 InvalidRequest."""

    async def go():
        iam = IdentityAccessManagement(
            [
                Identity(
                    name="admin",
                    credentials=[(ACCESS, SECRET)],
                    actions=["Admin"],
                ),
                Identity(name="anonymous", actions=["Read"]),
            ]
        )
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, with_s3=True,
            s3_kwargs=dict(iam=iam),
        )
        await cluster.start()
        signed = S3Client(cluster.s3.url, ACCESS, SECRET)
        anon = S3Client(cluster.s3.url)
        try:
            status, _, _ = await signed.request("PUT", "/payb")
            assert status == 200
            status, body, _ = await signed.request(
                "GET", "/payb", query="requestPayment"
            )
            assert status == 200
            assert _strip(_xml(body).tag) == "RequestPaymentConfiguration"
            payer = [c for c in _xml(body) if _strip(c.tag) == "Payer"]
            assert payer and payer[0].text == "BucketOwner"
            status, body, _ = await signed.request(
                "GET", "/no-such-bucket", query="requestPayment"
            )
            assert status == 404

            status, _, _ = await signed.request("PUT", "/payb/o.txt", b"pub")
            assert status == 200
            # the anonymous identity can read the object...
            status, body, _ = await anon.request("GET", "/payb/o.txt")
            assert status == 200 and body == b"pub"
            # ...but cannot rewrite its presentation headers
            status, body, _ = await anon.request(
                "GET", "/payb/o.txt",
                query="response-content-type=text/evil",
            )
            assert status == 400 and b"InvalidRequest" in body
            # a signed reader can
            status, _, hdrs = await signed.request(
                "GET", "/payb/o.txt",
                query="response-content-type=text/plain",
            )
            assert status == 200
            assert hdrs["Content-Type"].startswith("text/plain")
        finally:
            await cluster.stop()

    run(go())
