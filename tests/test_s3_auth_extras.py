"""S3 auth long tail: Signature V2 (header + presigned), POST policy
uploads, and verified STREAMING-AWS4-HMAC-SHA256-PAYLOAD chunk chains —
e2e against a live in-process cluster.  Reference:
weed/s3api/auth_signature_v2.go, s3api_object_handlers_postpolicy.go,
chunked_reader_v4.go."""
import asyncio
import base64
import hashlib
import hmac
import json
import time

import aiohttp

from seaweedfs_tpu.s3api import Identity, IdentityAccessManagement, sign_request_headers
from seaweedfs_tpu.s3api.auth import (
    STREAMING_PAYLOAD,
    _signature_v2,
    _signing_key,
    _string_to_sign_v2,
)
from seaweedfs_tpu.server.cluster import LocalCluster

ACCESS, SECRET = "AKV2EXAMPLE", "v2sekrit"


def run(coro):
    return asyncio.run(coro)


async def make_cluster(tmp_path):
    iam = IdentityAccessManagement(
        [Identity(name="admin", credentials=[(ACCESS, SECRET)], actions=["Admin"])]
    )
    cluster = LocalCluster(
        base_dir=str(tmp_path), n_volume_servers=1, with_s3=True,
        s3_kwargs=dict(iam=iam),
    )
    await cluster.start()
    return cluster


class _FakeReq:
    """Shape _string_to_sign_v2 needs for client-side signing."""

    def __init__(self, method, path, headers, query=None):
        self.method = method
        self.path = path
        self.headers = headers
        self.query = query or {}


def v2_headers(method: str, path: str, content_type: str = "") -> dict:
    h = {"Date": time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())}
    if content_type:
        h["Content-Type"] = content_type
    sts = _string_to_sign_v2(_FakeReq(method, path, h))
    h["Authorization"] = f"AWS {ACCESS}:{_signature_v2(SECRET, sts)}"
    return h


def test_sigv2_header_roundtrip(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        try:
            s3 = f"http://{cluster.s3.url}"
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    f"{s3}/v2bucket", headers=v2_headers("PUT", "/v2bucket", "application/octet-stream")
                ) as r:
                    assert r.status == 200, await r.text()
                async with s.put(
                    f"{s3}/v2bucket/obj.bin",
                    data=b"v2-data",
                    headers=v2_headers("PUT", "/v2bucket/obj.bin", "application/octet-stream"),
                ) as r:
                    assert r.status == 200, await r.text()
                async with s.get(
                    f"{s3}/v2bucket/obj.bin",
                    headers=v2_headers("GET", "/v2bucket/obj.bin"),
                ) as r:
                    assert r.status == 200
                    assert await r.read() == b"v2-data"
                # wrong secret is rejected
                bad = {
                    "Date": time.strftime(
                        "%a, %d %b %Y %H:%M:%S GMT", time.gmtime()
                    ),
                    "Authorization": f"AWS {ACCESS}:AAAAInvalidAAAA=",
                }
                async with s.get(f"{s3}/v2bucket/obj.bin", headers=bad) as r:
                    assert r.status == 403
        finally:
            await cluster.stop()

    run(go())


def test_sigv2_presigned_get(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        try:
            s3 = f"http://{cluster.s3.url}"
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    f"{s3}/v2p", headers=v2_headers("PUT", "/v2p", "application/octet-stream")
                ) as r:
                    assert r.status == 200
                async with s.put(
                    f"{s3}/v2p/x",
                    data=b"presigned",
                    headers=v2_headers("PUT", "/v2p/x", "application/octet-stream"),
                ) as r:
                    assert r.status == 200
                expires = int(time.time()) + 600
                sts = _string_to_sign_v2(
                    _FakeReq("GET", "/v2p/x", {}), date_value=str(expires)
                )
                sig = _signature_v2(SECRET, sts)
                import urllib.parse

                url = (
                    f"{s3}/v2p/x?AWSAccessKeyId={ACCESS}&Expires={expires}"
                    f"&Signature={urllib.parse.quote(sig, safe='')}"
                )
                async with s.get(url) as r:
                    assert r.status == 200
                    assert await r.read() == b"presigned"
                # expired link is rejected
                old = int(time.time()) - 10
                sts = _string_to_sign_v2(
                    _FakeReq("GET", "/v2p/x", {}), date_value=str(old)
                )
                sig = _signature_v2(SECRET, sts)
                url = (
                    f"{s3}/v2p/x?AWSAccessKeyId={ACCESS}&Expires={old}"
                    f"&Signature={urllib.parse.quote(sig, safe='')}"
                )
                async with s.get(url) as r:
                    assert r.status == 403
        finally:
            await cluster.stop()

    run(go())


def _signed_policy_form(bucket: str, key_prefix: str, max_size: int):
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    datestamp = amz_date[:8]
    credential = f"{ACCESS}/{datestamp}/us-east-1/s3/aws4_request"
    policy = {
        "expiration": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(time.time() + 600)
        ),
        "conditions": [
            {"bucket": bucket},
            ["starts-with", "$key", key_prefix],
            ["content-length-range", 1, max_size],
        ],
    }
    policy_b64 = base64.b64encode(json.dumps(policy).encode()).decode()
    key = _signing_key(SECRET, datestamp, "us-east-1", "s3")
    sig = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
    return {
        "policy": policy_b64,
        "x-amz-algorithm": "AWS4-HMAC-SHA256",
        "x-amz-credential": credential,
        "x-amz-date": amz_date,
        "x-amz-signature": sig,
    }


def test_post_policy_upload(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        try:
            s3 = f"http://{cluster.s3.url}"
            mk = sign_request_headers(
                "PUT", f"{s3}/forms", {}, b"", ACCESS, SECRET
            )
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{s3}/forms", headers=mk) as r:
                    assert r.status == 200

                def form(key, data, **extra):
                    fd = aiohttp.FormData()
                    fields = _signed_policy_form("forms", "uploads/", 1024)
                    fields.update(extra)
                    for k, v in fields.items():
                        fd.add_field(k, v)
                    fd.add_field("key", key)
                    fd.add_field("file", data, filename="f.txt")
                    return fd

                # happy path with ${filename} substitution and 201 XML
                async with s.post(
                    f"{s3}/forms",
                    data=form(
                        "uploads/${filename}", b"form-data",
                        success_action_status="201",
                    ),
                ) as r:
                    body = await r.text()
                    assert r.status == 201, body
                    assert "<Key>uploads/f.txt</Key>" in body
                get = sign_request_headers(
                    "GET", f"{s3}/forms/uploads/f.txt", {}, b"", ACCESS, SECRET
                )
                async with s.get(f"{s3}/forms/uploads/f.txt", headers=get) as r:
                    assert r.status == 200
                    assert await r.read() == b"form-data"

                # key outside the policy's starts-with prefix
                async with s.post(
                    f"{s3}/forms", data=form("elsewhere/evil", b"x")
                ) as r:
                    assert r.status == 403, await r.text()

                # over content-length-range
                async with s.post(
                    f"{s3}/forms", data=form("uploads/big", b"z" * 4096)
                ) as r:
                    assert r.status == 400

                # traversal in the key must not escape the bucket
                async with s.post(
                    f"{s3}/forms", data=form("uploads/../../other/x", b"x")
                ) as r:
                    assert r.status == 400

                # tampered signature
                fd = aiohttp.FormData()
                fields = _signed_policy_form("forms", "uploads/", 1024)
                fields["x-amz-signature"] = "0" * 64
                for k, v in fields.items():
                    fd.add_field(k, v)
                fd.add_field("key", "uploads/t")
                fd.add_field("file", b"x", filename="t")
                async with s.post(f"{s3}/forms", data=fd) as r:
                    assert r.status == 403
        finally:
            await cluster.stop()

    run(go())


def _frame_chunks(payload_chunks, secret, datestamp, amz_date, seed_sig):
    """Client-side aws-chunked framing with the V4 signature chain."""
    key = _signing_key(secret, datestamp, "us-east-1", "s3")
    scope = f"{datestamp}/us-east-1/s3/aws4_request"
    empty = hashlib.sha256(b"").hexdigest()
    prev = seed_sig
    out = bytearray()
    for chunk in [*payload_chunks, b""]:
        sts = "\n".join(
            [
                "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev,
                empty, hashlib.sha256(chunk).hexdigest(),
            ]
        )
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        out += f"{len(chunk):x};chunk-signature={sig}\r\n".encode()
        out += chunk + b"\r\n"
        prev = sig
    return bytes(out)


def test_streaming_chunked_signatures(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        try:
            s3 = f"http://{cluster.s3.url}"
            mk = sign_request_headers("PUT", f"{s3}/str", {}, b"", ACCESS, SECRET)
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{s3}/str", headers=mk) as r:
                    assert r.status == 200

                url = f"{s3}/str/chunked.bin"
                headers = sign_request_headers(
                    "PUT", url, {}, b"", ACCESS, SECRET,
                    payload_hash=STREAMING_PAYLOAD,
                )
                seed_sig = headers["Authorization"].rpartition("Signature=")[2]
                amz_date = headers["x-amz-date"]
                body = _frame_chunks(
                    [b"A" * 700, b"B" * 300], SECRET, amz_date[:8],
                    amz_date, seed_sig,
                )
                async with s.put(url, data=body, headers=headers) as r:
                    assert r.status == 200, await r.text()
                get = sign_request_headers("GET", url, {}, b"", ACCESS, SECRET)
                async with s.get(url, headers=get) as r:
                    assert await r.read() == b"A" * 700 + b"B" * 300

                # a tampered chunk breaks the chain -> rejected
                url2 = f"{s3}/str/tampered.bin"
                headers2 = sign_request_headers(
                    "PUT", url2, {}, b"", ACCESS, SECRET,
                    payload_hash=STREAMING_PAYLOAD,
                )
                seed2 = headers2["Authorization"].rpartition("Signature=")[2]
                d2 = headers2["x-amz-date"]
                evil = bytearray(
                    _frame_chunks([b"C" * 512], SECRET, d2[:8], d2, seed2)
                )
                evil[evil.find(b"C")] = ord("X")  # flip one payload byte
                async with s.put(url2, data=bytes(evil), headers=headers2) as r:
                    assert r.status == 403
                get2 = sign_request_headers("GET", url2, {}, b"", ACCESS, SECRET)
                async with s.get(url2, headers=get2) as r:
                    assert r.status == 404  # nothing stored
        finally:
            await cluster.stop()

    run(go())


def test_bucket_acl_and_skip_handlers(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        try:
            s3 = f"http://{cluster.s3.url}"
            mk = sign_request_headers("PUT", f"{s3}/aclb", {}, b"", ACCESS, SECRET)
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{s3}/aclb", headers=mk) as r:
                    assert r.status == 200
                g = sign_request_headers(
                    "GET", f"{s3}/aclb?acl=", {}, b"", ACCESS, SECRET
                )
                async with s.get(f"{s3}/aclb?acl=", headers=g) as r:
                    body = await r.text()
                    assert r.status == 200, body
                    assert "FULL_CONTROL" in body and ACCESS in body
                # PutBucketAcl mirrors the reference's NotImplemented
                p = sign_request_headers(
                    "PUT", f"{s3}/aclb?acl=", {}, b"", ACCESS, SECRET
                )
                async with s.put(f"{s3}/aclb?acl=", headers=p) as r:
                    assert r.status == 501
                # object acl/retention/legal-hold are documented no-ops
                put = sign_request_headers(
                    "PUT", f"{s3}/aclb/o.txt", {}, b"data", ACCESS, SECRET
                )
                async with s.put(f"{s3}/aclb/o.txt", data=b"data", headers=put) as r:
                    assert r.status == 200
                for sub in ("acl", "retention", "legal-hold"):
                    gg = sign_request_headers(
                        "GET", f"{s3}/aclb/o.txt?{sub}=", {}, b"", ACCESS, SECRET
                    )
                    async with s.get(f"{s3}/aclb/o.txt?{sub}=", headers=gg) as r:
                        assert r.status == 204, (sub, r.status)
        finally:
            await cluster.stop()

    run(go())


def test_bucket_lifecycle_view(tmp_path):
    """GET ?lifecycle reflects filer.conf TTL rules under the bucket."""

    async def go():
        import io

        from seaweedfs_tpu.shell import CommandEnv, run_command

        cluster = await make_cluster(tmp_path)
        try:
            s3 = f"http://{cluster.s3.url}"
            mk = sign_request_headers("PUT", f"{s3}/lc", {}, b"", ACCESS, SECRET)
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{s3}/lc", headers=mk) as r:
                    assert r.status == 200
                g = sign_request_headers(
                    "GET", f"{s3}/lc?lifecycle=", {}, b"", ACCESS, SECRET
                )
                async with s.get(f"{s3}/lc?lifecycle=", headers=g) as r:
                    assert r.status == 404  # no rules yet
                env = CommandEnv(
                    [cluster.master.advertise_url], out=io.StringIO()
                )
                await run_command(
                    env,
                    "fs.configure -locationPrefix /buckets/lc/logs/ "
                    "-ttl 48h -apply",
                )
                async with s.get(f"{s3}/lc?lifecycle=", headers=g) as r:
                    body = await r.text()
                    assert r.status == 200, body
                    assert "<Prefix>logs/</Prefix>" in body
                    assert "<Days>2</Days>" in body
                # DELETE actually clears the rules (not a lying 204)
                d = sign_request_headers(
                    "DELETE", f"{s3}/lc?lifecycle=", {}, b"", ACCESS, SECRET
                )
                async with s.delete(f"{s3}/lc?lifecycle=", headers=d) as r:
                    assert r.status == 204
                async with s.get(f"{s3}/lc?lifecycle=", headers=g) as r:
                    assert r.status == 404
        finally:
            await cluster.stop()

    run(go())
