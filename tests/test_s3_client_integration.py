"""Generic S3-protocol client wired four ways, e2e against the in-repo
S3 gateway: volume tier backend, remote-storage mount, replication sink,
and filer.backup target.

Reference counterparts: weed/storage/backend/s3_backend/s3_backend.go,
weed/remote_storage/s3/s3_storage_client.go,
weed/replication/sink/s3sink/s3_sink.go, and filer_backup.go's S3 sink —
all AWS-SDK-based there; here they ride s3api/client.py (signed by the
repo's own SigV4) so the whole protocol loop is testable with zero
egress: cluster A speaks S3 to cluster B's gateway.
"""
import argparse
import asyncio
import io
import os

import aiohttp
import pytest

from seaweedfs_tpu.command import COMMANDS
from seaweedfs_tpu.s3api import Identity, IdentityAccessManagement
from seaweedfs_tpu.s3api.client import S3Client
from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.storage import backend as backend_mod

ACCESS, SECRET = "AKIDTIER", "tier-secret"


def run_cmd(name, argv):
    mod = COMMANDS[name]
    p = argparse.ArgumentParser()
    mod.add_args(p)
    return mod.run(p.parse_args(argv))


async def start_object_cluster(tmp_path, auth=True):
    """Cluster B: the S3 endpoint everything else talks to."""
    iam = None
    if auth:
        iam = IdentityAccessManagement(
            [
                Identity(
                    name="tier",
                    credentials=[(ACCESS, SECRET)],
                    actions=["Admin"],
                )
            ]
        )
    cluster = LocalCluster(
        base_dir=str(tmp_path / "objstore"),
        n_volume_servers=1,
        pulse_seconds=1,
        with_s3=True,
        s3_kwargs=dict(iam=iam) if iam else {},
    )
    await cluster.start()
    return cluster


def s3_section(cluster, bucket, prefix=""):
    return {
        "type": "s3",
        "endpoint": cluster.s3.url,
        "bucket": bucket,
        "access_key": ACCESS,
        "secret_key": SECRET,
        "prefix": prefix,
        "create_bucket": True,
    }


@pytest.fixture(autouse=True)
def clean_registry():
    backend_mod.clear_registry()
    yield
    backend_mod.clear_registry()


def test_s3_client_roundtrip_and_multipart(tmp_path, monkeypatch):
    """Raw client against the gateway: objects, ranges, listing
    pagination, and the multipart path used for big tier uploads."""

    async def go():
        b = await start_object_cluster(tmp_path)
        try:
            client = S3Client(b.s3.url, ACCESS, SECRET)

            def drive():
                client.create_bucket("raw")
                client.create_bucket("raw")  # idempotent
                client.put_object("raw", "a/b.bin", b"hello world")
                assert client.get_object("raw", "a/b.bin") == b"hello world"
                assert client.get_object("raw", "a/b.bin", 6, 5) == b"world"
                assert client.head_object("raw", "a/b.bin") == 11
                with pytest.raises(FileNotFoundError):
                    client.head_object("raw", "missing")
                for i in range(7):
                    client.put_object("raw", f"many/k{i}", bytes([i]))
                keys = client.list_objects("raw", "many/", max_keys=3)
                assert [k for k, _ in keys] == [f"many/k{i}" for i in range(7)]
                # multipart: force the threshold down so a small file
                # exercises initiate/part/complete
                import seaweedfs_tpu.s3api.client as cmod

                monkeypatch.setattr(cmod, "MULTIPART_THRESHOLD", 1 << 20)
                monkeypatch.setattr(cmod, "PART_SIZE", 1 << 20)
                big = os.urandom(3 * (1 << 20) + 12345)
                src = tmp_path / "big.bin"
                src.write_bytes(big)
                assert client.put_object_from_file(
                    "raw", "big.bin", str(src)
                ) == len(big)
                assert client.head_object("raw", "big.bin") == len(big)
                assert client.get_object("raw", "big.bin", 2 << 20, 64) == big[
                    2 << 20 : (2 << 20) + 64
                ]
                dst = str(tmp_path / "back.bin")
                client.get_object_to_file("raw", "big.bin", dst)
                with open(dst, "rb") as f:
                    assert f.read() == big
                client.delete_object("raw", "a/b.bin")
                with pytest.raises(FileNotFoundError):
                    client.head_object("raw", "a/b.bin")

            await asyncio.to_thread(drive)
        finally:
            await b.stop()

    asyncio.run(go())


def test_tier_move_into_s3_gateway(tmp_path):
    """Cluster A tier-moves a volume into a bucket served by cluster B's
    S3 gateway and keeps serving reads from it (VERDICT round-2 'done'
    condition for the tier wiring)."""

    async def go():
        b = await start_object_cluster(tmp_path)
        a = LocalCluster(
            base_dir=str(tmp_path / "a"),
            n_volume_servers=1,
            pulse_seconds=1,
            volume_size_limit_mb=8,
        )
        await a.start()
        try:
            await asyncio.to_thread(
                backend_mod.configure, {"s3.default": s3_section(b, "tier")}
            )
            from seaweedfs_tpu.operation import assign, upload_data

            master = a.master.advertise_url
            a0 = await assign(master)
            vid = int(a0.fid.split(",")[0])
            blobs = {}
            for i in range(8):
                ai = await assign(master)
                if int(ai.fid.split(",")[0]) != vid:
                    continue
                data = os.urandom(4000 + i * 531)
                await upload_data(f"http://{ai.url}/{ai.fid}", data)
                blobs[ai.fid] = data
            assert blobs

            env = CommandEnv([master], out=io.StringIO())
            await run_command(env, "lock")
            await run_command(
                env, f"volume.tier.upload -volumeId {vid} -dest s3.default"
            )
            assert "uploaded" in env.out.getvalue()

            # the .dat now lives in the bucket...
            client = S3Client(b.s3.url, ACCESS, SECRET)
            keys = await asyncio.to_thread(client.list_objects, "tier")
            assert any(k.endswith(f"{vid}.dat") for k, _ in keys)
            # ...and reads still work, now through ranged S3 GETs
            async with aiohttp.ClientSession() as s:
                for fid, data in blobs.items():
                    vs = a.volume_servers[0]
                    async with s.get(f"http://{vs.url}/{fid}") as r:
                        assert r.status == 200
                        assert await r.read() == data

            # and back down
            await run_command(
                env, f"volume.tier.download -volumeId {vid}"
            )
            v = a.volume_servers[0].store.find_volume(vid)
            assert v is not None and not getattr(v, "remote_key", None)
        finally:
            await a.stop()
            await b.stop()

    asyncio.run(go())


def test_remote_mount_s3_bucket(tmp_path):
    """remote.configure -name s3.x / remote.mount of a gateway bucket:
    listing mirrors into the filer, reads stream through, remote.cache
    materializes chunks."""

    async def go():
        b = await start_object_cluster(tmp_path)
        a = LocalCluster(
            base_dir=str(tmp_path / "a"),
            n_volume_servers=1,
            pulse_seconds=1,
            with_filer=True,
        )
        await a.start()
        try:
            objects = {
                "photos/x.jpg": os.urandom(50_000),
                "photos/deep/y.bin": os.urandom(120_000),
                "top.txt": b"hello via s3",
            }
            client = S3Client(b.s3.url, ACCESS, SECRET)

            def seed():
                client.create_bucket("shared")
                for key, data in objects.items():
                    client.put_object("shared", key, data)

            await asyncio.to_thread(seed)

            env = CommandEnv([a.master.advertise_url], out=io.StringIO())
            await run_command(env, "lock")
            deadline = asyncio.get_event_loop().time() + 10
            while True:
                try:
                    await run_command(
                        env,
                        "remote.configure -name s3.ext "
                        f"-endpoint {b.s3.url} -bucket shared "
                        f"-accessKey {ACCESS} -secretKey {SECRET}",
                    )
                    break
                except Exception:
                    if asyncio.get_event_loop().time() > deadline:
                        raise
                    await asyncio.sleep(0.3)
            await run_command(env, "remote.mount -dir /ext -remote s3.ext")
            out = env.out.getvalue()
            assert "mounted s3.ext at /ext (3 objects)" in out

            async with aiohttp.ClientSession() as s:
                # read-through (no cached chunks yet)
                async with s.get(
                    f"http://{a.filer.url}/ext/photos/deep/y.bin"
                ) as r:
                    assert r.status == 200
                    assert await r.read() == objects["photos/deep/y.bin"]
                # cache, then read again
                await run_command(env, "remote.cache -dir /ext")
                async with s.get(f"http://{a.filer.url}/ext/top.txt") as r:
                    assert r.status == 200
                    assert await r.read() == objects["top.txt"]
        finally:
            await a.stop()
            await b.stop()

    asyncio.run(go())


def test_filer_replicate_into_s3_sink(tmp_path):
    """filer.replicate -targetRemote s3.x: the notification queue drains
    into a bucket; creates/deletes round-trip as objects."""

    async def go():
        from seaweedfs_tpu.replication.notification import FileQueueNotifier

        b = await start_object_cluster(tmp_path)
        spool = str(tmp_path / "events.spool")
        a = LocalCluster(
            base_dir=str(tmp_path / "a"),
            n_volume_servers=1,
            pulse_seconds=1,
            with_filer=True,
            filer_kwargs=dict(notifier=FileQueueNotifier(spool)),
        )
        await a.start()
        try:
            await asyncio.to_thread(
                backend_mod.configure, {"s3.sink": s3_section(b, "mirror")}
            )
            doc = os.urandom(64 * 1024)
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    f"http://{a.filer.url}/r/doc.bin", data=doc
                ) as r:
                    assert r.status in (200, 201)
                async with s.put(
                    f"http://{a.filer.url}/r/gone.bin", data=b"x"
                ) as r:
                    assert r.status in (200, 201)
                async with s.delete(f"http://{a.filer.url}/r/gone.bin") as r:
                    assert r.status < 400

            await run_cmd(
                "filer.replicate",
                [
                    "-spool", spool,
                    "-sourceFiler",
                    f"{a.filer.url}.{a.filer.grpc_port}",
                    "-targetRemote", "s3.sink/backup",
                    "-sourcePath", "/r",
                ],
            )
            client = S3Client(b.s3.url, ACCESS, SECRET)
            got = await asyncio.to_thread(
                client.get_object, "mirror", "backup/doc.bin"
            )
            assert got == doc
            with pytest.raises(FileNotFoundError):
                await asyncio.to_thread(
                    client.head_object, "mirror", "backup/gone.bin"
                )
        finally:
            await a.stop()
            await b.stop()

    asyncio.run(go())


def test_filer_backup_into_s3(tmp_path):
    """filer.backup -remote s3.x: one-shot replay lands the subtree as
    objects, with resumable progress stored in the bucket."""

    async def go():
        b = await start_object_cluster(tmp_path)
        a = LocalCluster(
            base_dir=str(tmp_path / "a"),
            n_volume_servers=1,
            pulse_seconds=1,
            with_filer=True,
        )
        await a.start()
        try:
            await asyncio.to_thread(
                backend_mod.configure, {"s3.bak": s3_section(b, "backups")}
            )
            files = {
                "/docs/a.txt": b"alpha",
                "/docs/sub/b.bin": os.urandom(30_000),
            }
            async with aiohttp.ClientSession() as s:
                for path, data in files.items():
                    async with s.put(
                        f"http://{a.filer.url}{path}", data=data
                    ) as r:
                        assert r.status in (200, 201)

            await run_cmd(
                "filer.backup",
                [
                    "-filer", f"{a.filer.url}.{a.filer.grpc_port}",
                    "-path", "/docs",
                    "-remote", "s3.bak/snap",
                    "-oneTime",
                ],
            )
            client = S3Client(b.s3.url, ACCESS, SECRET)

            def check():
                assert client.get_object("backups", "snap/a.txt") == files[
                    "/docs/a.txt"
                ]
                assert client.get_object("backups", "snap/sub/b.bin") == files[
                    "/docs/sub/b.bin"
                ]
                # progress marker written -> a rerun resumes, not replays
                assert int(
                    client.get_object(
                        "backups", "snap/.filer_backup_progress"
                    )
                ) > 0

            await asyncio.to_thread(check)
        finally:
            await a.stop()
            await b.stop()

    asyncio.run(go())
