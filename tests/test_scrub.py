"""EC parity scrub (ec.scrub / VolumeEcShardsVerify): recompute parity
over a mounted volume's shards and count mismatching bytes.

Three layers: the CPU file scrub (encoder.verify_ec_files), the
device-resident scrub (rs_resident.scrub_volume — only a [4] mismatch
vector leaves the device), and the volume-server RPC end-to-end (the
path bench.py times on the real TPU).  Reference analogue: the
read-verify passes of volume.fsck / ec.rebuild.
"""
import asyncio
import os

import numpy as np

from seaweedfs_tpu.ops import rs
from seaweedfs_tpu.ops.rs_resident import DeviceShardCache, scrub_volume
from seaweedfs_tpu.pb import Stub, channel, volume_server_pb2
from seaweedfs_tpu.storage.ec import encoder, layout


def run(coro):
    return asyncio.run(coro)


def _make_shards(tmp_path, mb=2, vid=7):
    base = str(tmp_path / str(vid))
    rng = np.random.default_rng(3)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, mb << 20, dtype=np.uint8).tobytes())
    encoder.write_ec_files(base, backend="cpu")
    return base


def test_file_scrub_clean_and_corrupt(tmp_path):
    base = _make_shards(tmp_path)
    mism, span = encoder.verify_ec_files(base, backend="cpu")
    assert mism == [0, 0, 0, 0]
    assert span == os.path.getsize(base + layout.to_ext(0))

    # one flipped byte in a PARITY shard -> exactly one mismatch there
    with open(base + layout.to_ext(12), "r+b") as f:
        f.seek(1234)
        b = f.read(1)
        f.seek(1234)
        f.write(bytes([b[0] ^ 0xFF]))
    mism, _ = encoder.verify_ec_files(base, backend="cpu")
    assert mism == [0, 0, 1, 0]

    # one flipped byte in a DATA shard -> that column's parity recomputes
    # differently in (almost surely) all four parity rows
    with open(base + layout.to_ext(3), "r+b") as f:
        f.seek(777)
        b = f.read(1)
        f.seek(777)
        f.write(bytes([b[0] ^ 0x5A]))
    mism, _ = encoder.verify_ec_files(base, backend="cpu")
    assert mism[2] >= 1 and sum(1 for v in mism if v >= 1) >= 3


def test_resident_scrub_matches_file_scrub(tmp_path):
    base = _make_shards(tmp_path)
    cache = DeviceShardCache(budget_bytes=1 << 30)
    for sid in range(layout.TOTAL_SHARDS):
        cache.put(7, sid, np.fromfile(base + layout.to_ext(sid), np.uint8))
    mism, span = scrub_volume(cache, 7)
    assert mism == [0, 0, 0, 0]
    assert span >= os.path.getsize(base + layout.to_ext(0))

    # corrupt the RESIDENT copy of a parity shard: the scrub sees memory,
    # not files
    bad = np.fromfile(base + layout.to_ext(11), np.uint8)
    bad[4096] ^= 0x01
    cache.put(7, 11, bad)
    mism, _ = scrub_volume(cache, 7)
    assert mism == [0, 1, 0, 0]
    cache.clear()


def test_scrub_rpc_end_to_end(tmp_path):
    """VolumeEcShardsVerify through a live volume server: the resident
    backend when the cache holds the volume, the CPU backend otherwise,
    and corruption detected through the same RPC."""
    from test_serving_e2e import _build_degraded_cluster

    async def go():
        cluster, vs, _ = await _build_degraded_cluster(
            tmp_path, n_blobs=6, device_cache=True, drop_shards=()
        )
        try:
            vid = next(iter(vs.store.ec_device_cache.resident_by_vid()))
            stub = Stub(channel(vs.grpc_url), volume_server_pb2, "VolumeServer")
            r = await stub.VolumeEcShardsVerify(
                volume_server_pb2.VolumeEcShardsVerifyRequest(volume_id=vid)
            )
            assert list(r.parity_mismatch_bytes) == [0, 0, 0, 0]
            assert r.backend == "device_resident"
            assert r.bytes_verified > 0 and r.seconds >= 0

            # corrupt one resident parity shard -> RPC reports it
            ev = vs.store.find_ec_volume(vid)
            bad = np.fromfile(
                ev.base_name + layout.to_ext(13), np.uint8
            )
            bad[100] ^= 0x40
            vs.store.ec_device_cache.put(vid, 13, bad)
            r = await stub.VolumeEcShardsVerify(
                volume_server_pb2.VolumeEcShardsVerifyRequest(volume_id=vid)
            )
            assert list(r.parity_mismatch_bytes) == [0, 0, 0, 1]

            # cache dropped -> same RPC serves from the files on the CPU
            vs.store.ec_device_cache.clear()
            r = await stub.VolumeEcShardsVerify(
                volume_server_pb2.VolumeEcShardsVerifyRequest(volume_id=vid)
            )
            assert list(r.parity_mismatch_bytes) == [0, 0, 0, 0]
            assert r.backend in ("native", "numpy")
        finally:
            await cluster.stop()

    run(go())


def test_scrub_shell_command(tmp_path):
    """`ec.scrub` reports OK for a clean co-located volume."""
    from test_serving_e2e import _build_degraded_cluster

    async def go():
        cluster, vs, _ = await _build_degraded_cluster(
            tmp_path, n_blobs=6, device_cache=False, drop_shards=()
        )
        try:
            from seaweedfs_tpu.shell.command_env import CommandEnv
            from seaweedfs_tpu.shell.commands import COMMANDS

            lines = []
            env = CommandEnv([cluster.master.advertise_url])
            env.write = lambda s: lines.append(s)
            # the mounted shards reach the master via the next heartbeat
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                lines.clear()
                await COMMANDS["ec.scrub"](env, [])
                if lines:
                    break
                await asyncio.sleep(0.3)
            assert any("OK" in l for l in lines), lines
        finally:
            await cluster.stop()

    run(go())


def test_auto_scrub_loop_detects_corruption(tmp_path):
    """-ec.scrub.intervalSeconds: the volume server's background scrub
    finds a corrupted parity shard and raises the corrupt-volume gauge;
    a clean pass later clears it."""
    import time as time_mod

    from seaweedfs_tpu import stats
    from seaweedfs_tpu.server.volume import VolumeServer

    base = _make_shards(tmp_path, vid=1)

    async def go():
        # minimal sidecars BEFORE construction: discovery scans at init
        from seaweedfs_tpu.storage.volume_info import save_volume_info

        save_volume_info(base + ".vif", {"version": 3})
        # graftlint: allow(async-blocking): test fixture touch, nothing
        # else shares this loop
        open(base + ".ecx", "ab").close()
        vs = VolumeServer(
            masters=[], directories=[str(tmp_path)], port=0, grpc_port=0,
            ec_backend="cpu", ec_scrub_interval_seconds=1,
        )
        await vs.start(heartbeat=False)
        try:
            deadline = time_mod.time() + 15
            while time_mod.time() < deadline:
                if stats.VOLUME_SERVER_SCRUB_CORRUPT_GAUGE._value.get() == 0:
                    break
                await asyncio.sleep(0.2)

            # corrupt a parity shard on disk -> next cycle flags it
            # graftlint: allow(async-blocking): 1-byte test patch, nothing
            # else shares this loop
            with open(base + layout.to_ext(10), "r+b") as f:
                f.seek(64)
                b = f.read(1)
                f.seek(64)
                f.write(bytes([b[0] ^ 0x80]))
            deadline = time_mod.time() + 20
            while time_mod.time() < deadline:
                if stats.VOLUME_SERVER_SCRUB_CORRUPT_GAUGE._value.get() == 1:
                    break
                await asyncio.sleep(0.2)
            assert stats.VOLUME_SERVER_SCRUB_CORRUPT_GAUGE._value.get() == 1

            # repair (restore the byte) -> gauge clears
            # graftlint: allow(async-blocking): 1-byte test patch, nothing
            # else shares this loop
            with open(base + layout.to_ext(10), "r+b") as f:
                f.seek(64)
                f.write(bytes([b[0]]))
            deadline = time_mod.time() + 20
            while time_mod.time() < deadline:
                if stats.VOLUME_SERVER_SCRUB_CORRUPT_GAUGE._value.get() == 0:
                    break
                await asyncio.sleep(0.2)
            assert stats.VOLUME_SERVER_SCRUB_CORRUPT_GAUGE._value.get() == 0
        finally:
            await vs.stop()

    run(go())
