"""JWT write auth + Prometheus metrics.

Reference behavior: the master signs an HS256 JWT over each assigned fid
(/root/reference/weed/security/jwt.go:30-50); the volume server rejects
writes without a valid matching token
(volume_server_handlers.go:145-187); every server exposes /metrics
(stats/metrics.go:30-300).
"""
import asyncio
import os
import time

import aiohttp
import pytest

from seaweedfs_tpu.operation import assign, upload_data
from seaweedfs_tpu.security import (
    JwtError,
    decode_jwt,
    encode_jwt,
    gen_volume_write_jwt,
    verify_volume_write_jwt,
)
from seaweedfs_tpu.server.cluster import LocalCluster


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------- unit: jwt


def test_jwt_roundtrip():
    tok = encode_jwt("secret", {"fid": "3,01abcd", "exp": int(time.time()) + 60})
    claims = decode_jwt("secret", tok)
    assert claims["fid"] == "3,01abcd"


def test_jwt_bad_signature():
    tok = encode_jwt("secret", {"fid": "3,01abcd"})
    with pytest.raises(JwtError):
        decode_jwt("other-key", tok)


def test_jwt_tampered_payload():
    tok = encode_jwt("secret", {"fid": "3,01abcd"})
    head, payload, sig = tok.split(".")
    other = encode_jwt("secret", {"fid": "9,ffffff"}).split(".")[1]
    with pytest.raises(JwtError):
        decode_jwt("secret", f"{head}.{other}.{sig}")


def test_jwt_expired():
    tok = encode_jwt("secret", {"fid": "3,01abcd", "exp": int(time.time()) - 5})
    with pytest.raises(JwtError):
        decode_jwt("secret", tok)


def test_jwt_malformed():
    for bad in ("", "x", "a.b", "a.b.c.d", "!!.??.!!"):
        with pytest.raises(JwtError):
            decode_jwt("secret", bad)


def test_gen_volume_write_jwt_empty_key():
    assert gen_volume_write_jwt("", "3,01abcd") == ""


class _FakeRequest:
    def __init__(self, query=None, headers=None):
        self.query = query or {}
        self.headers = headers or {}


def test_verify_write_jwt_fid_match_and_batch_suffix():
    key = "k"
    tok = gen_volume_write_jwt(key, "3,01abcd")
    req = _FakeRequest(headers={"Authorization": f"Bearer {tok}"})
    assert verify_volume_write_jwt(key, req, "3,01abcd")
    # count>1 uploads use fid_N against the same base-fid token
    assert verify_volume_write_jwt(key, req, "3,01abcd_2")
    assert not verify_volume_write_jwt(key, req, "3,99ffff")
    # query-param transport (jwt.go GetJwt)
    assert verify_volume_write_jwt(key, _FakeRequest(query={"jwt": tok}), "3,01abcd")
    assert not verify_volume_write_jwt(key, _FakeRequest(), "3,01abcd")
    # no key configured -> open
    assert verify_volume_write_jwt("", _FakeRequest(), "3,01abcd")


# ---------------------------------------------------------------- e2e


async def fetch(url, method="GET", **kw):
    async with aiohttp.ClientSession() as s:
        async with s.request(method, url, **kw) as r:
            return r.status, await r.read()


def test_jwt_guards_volume_writes(tmp_path):
    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), with_filer=True, jwt_signing_key="t0psecret"
        )
        await cluster.start()
        try:
            master = cluster.master.advertise_url
            a = await assign(master)
            assert a.auth, "assign must return a signed write token"
            url = f"http://{a.url}/{a.fid}"
            payload = os.urandom(1024)

            # unauthenticated direct write -> 401
            status, _ = await fetch(url, "POST", data=payload)
            assert status == 401

            # wrong-key token -> 401
            bad = gen_volume_write_jwt("wrong-key", a.fid)
            status, _ = await fetch(
                url, "POST", data=payload, headers={"Authorization": f"Bearer {bad}"}
            )
            assert status == 401

            # the assign-issued token authorizes the write
            result = await upload_data(url, payload, "x.bin", jwt=a.auth)
            assert result["size"] > 0

            # reads stay open (no read signing key configured)
            status, body = await fetch(url)
            assert status == 200 and body == payload

            # delete without a token -> 401; with the token -> ok
            status, _ = await fetch(url, "DELETE")
            assert status == 401
            status, _ = await fetch(
                url, "DELETE", headers={"Authorization": f"Bearer {a.auth}"}
            )
            assert status == 200

            # the filer pipes assign auth through to its chunk uploads
            status, _ = await fetch(
                f"http://{cluster.filer.ip}:{cluster.filer.port}/d/f.bin",
                "PUT",
                data=os.urandom(2048),
            )
            assert status in (200, 201)

            # client delete flow fetches its write token via LookupVolume
            from seaweedfs_tpu.operation import delete_file, submit_data

            fid = await submit_data(master, b"short-lived")
            assert await delete_file(master, fid)
        finally:
            await cluster.stop()

    run(go())


def test_metrics_endpoints(tmp_path):
    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), with_filer=True, jwt_signing_key="k"
        )
        await cluster.start()
        try:
            master = cluster.master
            a = await assign(master.advertise_url)
            await upload_data(
                f"http://{a.url}/{a.fid}", b"metrics-payload", "m.bin", jwt=a.auth
            )
            await fetch(f"http://{a.url}/{a.fid}")

            status, body = await fetch(f"http://{master.ip}:{master.port}/metrics")
            assert status == 200
            assert b"SeaweedFS_master_received_heartbeats" in body

            vs = cluster.volume_servers[0]
            status, body = await fetch(f"http://{vs.ip}:{vs.port}/metrics")
            assert status == 200
            assert b"SeaweedFS_volumeServer_request_total" in body
            assert b"SeaweedFS_volumeServer_volumes" in body

            # filer metrics live on a dedicated port so the namespace path
            # "/metrics" stays a regular file path
            f = cluster.filer
            status, body = await fetch(f"http://{f.ip}:{f.metrics_port}/metrics")
            assert status == 200
            assert b"SeaweedFS_filer_request_total" in body
            status, _ = await fetch(
                f"http://{f.ip}:{f.port}/metrics", "PUT", data=b"a file"
            )
            assert status in (200, 201)
            status, body = await fetch(f"http://{f.ip}:{f.port}/metrics")
            assert status == 200 and body == b"a file"
        finally:
            await cluster.stop()

    run(go())
