"""mTLS for the gRPC control plane + IP-whitelist Guard.

Reference: weed/security/tls.go (security.toml-driven TLS on every gRPC
surface) and guard.go:52-105 (white_list).  The e2e test runs a full
cluster with mutual TLS configured: heartbeats, assigns, filer metadata
RPCs and uploads all ride TLS channels; a plaintext client is rejected
at the handshake.
"""
import asyncio

import aiohttp
import grpc
import pytest

from seaweedfs_tpu.pb import Stub, master_pb2
from seaweedfs_tpu.pb.rpc import GRPC_OPTIONS
from seaweedfs_tpu.security import tls as tls_mod
from seaweedfs_tpu.security.guard import Guard
from seaweedfs_tpu.server.cluster import LocalCluster


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def pki(tmp_path):
    cfg = tls_mod.generate_test_pki(str(tmp_path / "pki"))
    tls_mod.configure(cfg)
    yield cfg
    tls_mod.configure(None)


class TestGuard:
    def test_rules(self):
        g = Guard(["127.0.0.1", "10.0.0.0/8", "::1"])
        assert g.enabled
        assert g.allowed("127.0.0.1")
        assert g.allowed("10.3.4.5")
        assert g.allowed("::1")
        assert not g.allowed("192.168.1.1")
        assert not g.allowed("not-an-ip")

    def test_empty_is_open(self):
        g = Guard([])
        assert not g.enabled
        assert g.allowed("8.8.8.8")

    def test_http_rejection(self, tmp_path):
        async def go():
            cluster = LocalCluster(
                base_dir=str(tmp_path), n_volume_servers=1, pulse_seconds=1,
                master_kwargs=dict(white_list=["10.0.0.0/8"]),
            )
            await cluster.start()
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.get(
                        f"http://{cluster.master.url}/dir/assign"
                    ) as r:
                        assert r.status == 403  # we come from 127.0.0.1
            finally:
                await cluster.stop()

        run(go())

    def test_http_allowed(self, tmp_path):
        async def go():
            cluster = LocalCluster(
                base_dir=str(tmp_path), n_volume_servers=1, pulse_seconds=1,
                master_kwargs=dict(white_list=["127.0.0.0/8"]),
            )
            await cluster.start()
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.get(
                        f"http://{cluster.master.url}/dir/assign"
                    ) as r:
                        assert r.status in (200, 404)  # allowed through
            finally:
                await cluster.stop()

        run(go())


class TestClusterTls:
    def test_cluster_e2e_with_mtls(self, tmp_path, pki):
        """Full write/read path with every gRPC hop on mutual TLS."""

        async def go():
            cluster = LocalCluster(
                base_dir=str(tmp_path / "c"), n_volume_servers=2,
                pulse_seconds=1, with_filer=True,
            )
            await cluster.start()
            try:
                # data path: filer upload (filer->master AssignVolume and
                # filer meta RPCs all ride TLS channels)
                import os

                blob = os.urandom(200_000)
                async with aiohttp.ClientSession() as s:
                    async with s.put(
                        f"http://{cluster.filer.url}/tls/doc.bin", data=blob
                    ) as r:
                        assert r.status in (200, 201)
                    async with s.get(
                        f"http://{cluster.filer.url}/tls/doc.bin"
                    ) as r:
                        assert r.status == 200
                        assert await r.read() == blob

                # a TLS client with the right certs can talk gRPC
                creds = tls_mod.channel_credentials(pki)
                ch = grpc.aio.secure_channel(
                    cluster.master.grpc_url, creds, options=GRPC_OPTIONS
                )
                stub = Stub(ch, master_pb2, "Seaweed")
                resp = await stub.Assign(master_pb2.AssignRequest(count=1))
                assert resp.fid
                await ch.close()

                # a PLAINTEXT client is rejected at the transport
                plain = grpc.aio.insecure_channel(
                    cluster.master.grpc_url, options=GRPC_OPTIONS
                )
                pstub = Stub(plain, master_pb2, "Seaweed")
                with pytest.raises(grpc.aio.AioRpcError):
                    await asyncio.wait_for(
                        pstub.Assign(master_pb2.AssignRequest(count=1)), 10
                    )
                await plain.close()
            finally:
                await cluster.stop()

        run(go())
