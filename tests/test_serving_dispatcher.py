"""Unit suite for the continuous-batching EC serving subsystem
(seaweedfs_tpu/serving/): coalescer packing rules, the dispatcher's
admission window, pipelined in-flight depth, backpressure fallback, and
batched-vs-unbatched result identity — all against a fake store, so the
batching semantics are pinned without booting a cluster.  The real-path
integration (HTTP -> dispatcher -> device cache) lives in
tests/test_serving_e2e.py.
"""
import asyncio
import threading
import time

import pytest

from seaweedfs_tpu import stats
from seaweedfs_tpu.serving import (
    Coalescer,
    EcReadDispatcher,
    ReadRequest,
    ServingConfig,
)


def run(coro):
    return asyncio.run(coro)


def req(vid, nid):
    loop = asyncio.get_running_loop()
    return ReadRequest(vid, nid, None, loop.create_future(), loop.time())


# --------------------------------------------------------------- coalescer


def test_coalescer_packs_fifo_and_groups_by_vid():
    async def go():
        c = Coalescer(max_batch=4, max_queue=100)
        for i in range(6):
            assert c.offer(req(vid=i % 2, nid=i))
        assert len(c) == 6
        groups = c.take()  # first 4 in arrival order, grouped by vid
        assert {v: [r.nid for r in rs] for v, rs in groups.items()} == {
            0: [0, 2],
            1: [1, 3],
        }
        assert len(c) == 2  # the overflow stays queued for the next take
        groups = c.take()
        assert {v: [r.nid for r in rs] for v, rs in groups.items()} == {
            0: [4],
            1: [5],
        }
        assert c.take() == {}

    run(go())


def test_coalescer_saturation_rejects():
    async def go():
        c = Coalescer(max_batch=2, max_queue=3)
        assert [c.offer(req(1, i)) for i in range(5)] == [
            True, True, True, False, False,
        ]
        c.take()
        assert c.offer(req(1, 9))  # drained below the limit: admits again

    run(go())


# -------------------------------------------------------------- dispatcher


class FakeStore:
    """Deterministic store double: batch and native paths return the
    same value for the same needle, so identity is checkable."""

    def __init__(self, resident=True, batch_sleep=0.0, gate=None):
        self.resident = resident
        self.batch_calls: list[list[int]] = []
        self.native_calls: list[int] = []
        self.batch_sleep = batch_sleep
        self.gate = gate  # threading.Event: batch blocks until set
        self._active = 0
        self.peak_active = 0
        self._lock = threading.Lock()

    def ec_volume_is_resident(self, vid):
        return self.resident

    def _value(self, vid, nid):
        return f"needle-{vid}-{nid}".encode()

    def read_ec_needles_batch(
        self, vid, requests, remote_read=None, zero_copy=False
    ):
        with self._lock:
            self._active += 1
            self.peak_active = max(self.peak_active, self._active)
            self.batch_calls.append([nid for nid, _ in requests])
        if self.gate is not None:
            self.gate.wait(5)
        if self.batch_sleep:
            time.sleep(self.batch_sleep)
        with self._lock:
            self._active -= 1
        out = []
        for nid, _cookie in requests:
            if nid == 666:
                out.append(KeyError("corrupt needle"))
            else:
                out.append(self._value(vid, nid))
        return out

    def read_ec_needle(
        self, vid, nid, cookie=None, remote_read=None, use_device=True,
        zero_copy=False,
    ):
        self.native_calls.append(nid)
        if nid == 666:
            raise KeyError("corrupt needle")
        return self._value(vid, nid)


def make(store, **kw):
    defaults = dict(max_inflight=1, max_wait_us=0)
    defaults.update(kw)
    return EcReadDispatcher(store, lambda vid: None, ServingConfig(**defaults))


def test_batched_results_byte_identical_to_unbatched():
    """The satellite contract: a concurrent burst served through the
    coalescer/pipeline returns byte-identical results to the native
    per-read path, with per-needle failures isolated."""

    async def go():
        store = FakeStore()
        d = make(store, max_inflight=3, max_wait_us=100)
        nids = list(range(40)) + [666]
        batched = await asyncio.gather(
            *(d.read(7, n, None) for n in nids), return_exceptions=True
        )
        for n, got in zip(nids, batched):
            if n == 666:
                assert isinstance(got, KeyError)
            else:
                assert got == store.read_ec_needle(7, n)
        # and the burst actually rode the batch path
        assert sum(len(b) for b in store.batch_calls) == len(nids)
        assert max(len(b) for b in store.batch_calls) > 1

    run(go())


def test_max_batch_splits_wide_bursts():
    async def go():
        store = FakeStore()
        d = make(store, max_batch=8, max_queue=1000)
        await asyncio.gather(*(d.read(1, n, None) for n in range(30)))
        assert max(len(b) for b in store.batch_calls) <= 8

    run(go())


def test_admission_window_fills_partial_batches():
    """A hot lane holds the max-wait window open so stragglers join the
    next batch instead of fragmenting into singletons; max_wait_us=0
    disables the window."""

    async def go(max_wait_us):
        gate = threading.Event()
        store = FakeStore(gate=gate)
        d = make(store, max_wait_us=max_wait_us)
        first = asyncio.ensure_future(d.read(1, 0, None))
        while not store.batch_calls:  # lane is now blocked in batch #1
            await asyncio.sleep(0.001)
        second = asyncio.ensure_future(d.read(1, 1, None))
        await asyncio.sleep(0.001)

        async def trickle():
            # lands inside a 100ms window, after a 0-width one closed
            await asyncio.sleep(0.02)
            return await d.read(1, 2, None)

        third = asyncio.ensure_future(trickle())
        gate.set()
        await asyncio.gather(first, second, third)
        return store.batch_calls

    calls = run(go(max_wait_us=100_000))
    assert calls[0] == [0]
    assert calls[1] == [1, 2], calls  # window held open for the straggler
    calls = run(go(max_wait_us=0))
    assert calls[1] == [1], calls  # no window: dispatches what is queued


def test_pipelined_batches_overlap():
    """max_inflight lanes genuinely overlap device calls: with 3 lanes
    and slow batches, at least two read_ec_needles_batch calls must be
    active at once (the continuous-batching property round 5 lacked)."""

    async def go():
        store = FakeStore(batch_sleep=0.05)
        d = make(store, max_inflight=3, max_batch=4, max_wait_us=0)
        await asyncio.gather(*(d.read(1, n, None) for n in range(24)))
        assert store.peak_active >= 2, store.batch_calls

    run(go())


def test_backpressure_falls_back_to_native():
    """Past max_queue the dispatcher sheds to the native path (counted
    in the fallback series) and every request still gets the right
    bytes."""

    async def go():
        gate = threading.Event()
        store = FakeStore(gate=gate)
        d = make(store, max_batch=2, max_queue=2)
        fallback0 = stats.VOLUME_SERVER_EC_BATCH_FALLBACK._value.get()
        first = asyncio.ensure_future(d.read(1, 0, None))
        while not store.batch_calls:
            await asyncio.sleep(0.001)
        # queue capacity is 2: the next two queue, the rest shed native
        rest = [asyncio.ensure_future(d.read(1, n, None)) for n in range(1, 8)]
        while len(store.native_calls) < 5:
            await asyncio.sleep(0.001)
        gate.set()
        results = await asyncio.gather(first, *rest)
        assert results == [store._value(1, n) for n in range(8)]
        assert len(store.native_calls) == 5
        shed = stats.VOLUME_SERVER_EC_BATCH_FALLBACK._value.get() - fallback0
        assert shed == 5

    run(go())


def test_non_resident_volume_routes_native():
    """An unpinned volume's reads never queue behind a batch — they run
    concurrently on the native path (the round-5 serialization hazard)."""

    async def go():
        store = FakeStore(resident=False)
        d = make(store)
        out = await asyncio.gather(*(d.read(3, n, None) for n in range(6)))
        assert out == [store._value(3, n) for n in range(6)]
        assert store.batch_calls == []
        assert store.native_calls == list(range(6))

    run(go())


def test_disabled_dispatcher_routes_native():
    async def go():
        store = FakeStore(resident=True)
        d = make(store, enabled=False)
        assert await d.read(1, 5, None) == store._value(1, 5)
        assert store.batch_calls == [] and store.native_calls == [5]

    run(go())


def test_config_validation():
    with pytest.raises(ValueError):
        ServingConfig(max_batch=0).validated()
    with pytest.raises(ValueError):
        ServingConfig(max_queue=4, max_batch=8).validated()
    with pytest.raises(ValueError):
        ServingConfig(max_inflight=0).validated()
    with pytest.raises(ValueError):
        ServingConfig(max_wait_us=-1).validated()


def test_dispatch_metrics_observed():
    """The observability series move: batch-size histogram counts the
    batches, queue-wait observes per request, occupancy returns to 0,
    and the route counter splits batched vs native."""

    async def go():
        size_hist = stats.VOLUME_SERVER_EC_BATCH_SIZE
        wait_hist = stats.VOLUME_SERVER_EC_BATCH_QUEUE_WAIT
        batched = stats.VOLUME_SERVER_EC_READ_ROUTE.labels(route="batched")
        native = stats.VOLUME_SERVER_EC_READ_ROUTE.labels(route="native")
        s0 = size_hist._sum.get()
        # bucket counters are per-bucket internally; the sum is the
        # observation count
        w0 = sum(b.get() for b in wait_hist._buckets)
        b0 = batched._value.get()
        n0 = native._value.get()

        store = FakeStore()
        d = make(store, max_inflight=2, max_wait_us=100)
        await asyncio.gather(*(d.read(1, n, None) for n in range(12)))
        await d.read(2, 0, None)
        store.resident = False
        await d.read(1, 99, None)

        assert size_hist._sum.get() - s0 == 13  # every batched read counted
        assert sum(b.get() for b in wait_hist._buckets) - w0 == 13
        assert batched._value.get() - b0 == 13
        assert native._value.get() - n0 == 1
        assert stats.VOLUME_SERVER_EC_BATCH_INFLIGHT._value.get() == 0

    run(go())
