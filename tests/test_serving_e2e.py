"""Integrated degraded-read serving e2e: HTTP reads through the volume
server's EcReadBatcher -> Store.read_ec_needles_batch -> EcVolume
resident cache -> batched reconstruct calls, with two shards destroyed
so every read MUST reconstruct.

This is the CI-scaled promotion of the round-4 hardware drive
(experiments/r4_serving_e2e.py): same cluster wiring, same
encode/mount/pin/degrade sequence, byte-exactness asserted for
sequential reads, coalesced concurrent bursts, and the no-cache native
path — on the CPU backend (tests/conftest.py forces JAX cpu; the device
cache runs the XLA fallback kernels).  bench.py's serving sweep runs the
same path on the real TPU and publishes the measured numbers.

Reference path being matched: weed/storage/store_ec.go:136-393.
"""
import asyncio
import os
import tempfile
import time

import aiohttp
import numpy as np
import pytest

from seaweedfs_tpu.operation import assign, upload_data
from seaweedfs_tpu.pb import Stub, channel, volume_server_pb2
from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.storage.ec.layout import TOTAL_SHARDS


def run(coro):
    return asyncio.run(coro)


async def _build_degraded_cluster(tmp_path, n_blobs=10, device_cache=True):
    """Cluster with one volume EC-encoded, mounted, and two shards
    destroyed; returns (cluster, vs, blobs dict fid->bytes)."""
    cluster = LocalCluster(
        base_dir=str(tmp_path), n_volume_servers=1, pulse_seconds=1,
    )
    await cluster.start()
    vs = cluster.volume_servers[0]
    if device_cache:
        from seaweedfs_tpu.ops.rs_resident import DeviceShardCache

        vs.store.ec_device_cache = DeviceShardCache(budget_bytes=1 << 30)

    master = cluster.master.advertise_url
    rng = np.random.default_rng(11)
    blobs = {}
    vid = None
    for i in range(120):
        if len(blobs) >= n_blobs:
            break
        a = await assign(master)
        v = int(a.fid.split(",")[0])
        if vid is None:
            vid = v
        if v != vid:  # assigns round-robin over several volumes
            continue
        data = rng.integers(0, 256, 1500 + i * 613, dtype=np.uint8).tobytes()
        await upload_data(f"http://{a.url}/{a.fid}", data)
        blobs[a.fid] = data
    assert len(blobs) >= max(6, n_blobs // 2)

    stub = Stub(channel(vs.grpc_url), volume_server_pb2, "VolumeServer")
    await stub.VolumeMarkReadonly(
        volume_server_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
    )
    await stub.VolumeEcShardsGenerate(
        volume_server_pb2.VolumeEcShardsGenerateRequest(volume_id=vid)
    )
    await stub.VolumeEcShardsMount(
        volume_server_pb2.VolumeEcShardsMountRequest(
            volume_id=vid, shard_ids=list(range(TOTAL_SHARDS))
        )
    )
    await stub.VolumeUnmount(
        volume_server_pb2.VolumeUnmountRequest(volume_id=vid)
    )
    if device_cache:
        # wait for the async HBM pin + warm thread
        deadline = time.time() + 120
        while time.time() < deadline:
            if len(vs.store.ec_device_cache.shard_ids(vid)) == TOTAL_SHARDS:
                break
            await asyncio.sleep(0.1)
        assert (
            len(vs.store.ec_device_cache.shard_ids(vid)) == TOTAL_SHARDS
        ), "shards never became resident"

    # force DEGRADED reads: shard 0 holds every needle of a small volume
    # (intervals start at offset 0), so removing it makes every read
    # reconstruct; removing shard 11 too drops redundancy to exactly 10.
    for sid in (0, 11):
        await stub.VolumeEcShardsUnmount(
            volume_server_pb2.VolumeEcShardsUnmountRequest(
                volume_id=vid, shard_ids=[sid]
            )
        )
        if device_cache:
            vs.store.ec_device_cache.evict(vid, sid)
        base = vs.store._ec_base(vid, "")
        p = base + f".ec{sid:02d}"
        if os.path.exists(p):
            os.remove(p)
    return cluster, vs, blobs


@pytest.mark.parametrize("device_cache", [True, False])
def test_degraded_http_serving_byte_exact(tmp_path, device_cache):
    """Every blob reads back byte-exact over plain HTTP with two shards
    destroyed — through the batcher + resident cache when enabled, and
    through the per-read native reconstruct path when not."""

    async def go():
        cluster, vs, blobs = await _build_degraded_cluster(
            tmp_path, device_cache=device_cache
        )
        try:
            async with aiohttp.ClientSession() as sess:

                async def read(fid):
                    async with sess.get(f"http://{vs.url}/{fid}") as r:
                        assert r.status == 200, (fid, r.status)
                        return await r.read()

                # sequential correctness pass
                for fid, want in blobs.items():
                    got = await read(fid)
                    assert got == want, f"{fid}: degraded read corrupt"

                # concurrent burst: the batcher coalesces (device-cache
                # mode) or fans out per-read (native mode); both must
                # stay byte-exact under concurrency
                fids = list(blobs) * 3
                results = await asyncio.gather(*(read(f) for f in fids))
                for f, got in zip(fids, results):
                    assert got == blobs[f]

                # missing needle still 404s cleanly through the batcher
                bad_fid = next(iter(blobs)).split(",")[0] + ",ffffffffffffffff"
                async with sess.get(f"http://{vs.url}/{bad_fid}") as r:
                    assert r.status == 404
        finally:
            await cluster.stop()

    run(go())


def test_degraded_serving_batcher_coalesces(tmp_path):
    """The concurrent burst actually rides the batch path: after the
    burst, the batcher has seen multi-needle batches (not 1-by-1), and
    repeated bursts return stable results (compile caches warm)."""

    async def go():
        cluster, vs, blobs = await _build_degraded_cluster(
            tmp_path, n_blobs=8, device_cache=True
        )
        try:
            seen_widths = []
            store = vs.store
            orig = store.read_ec_needles_batch

            def spying(vid, requests, remote_read=None):
                seen_widths.append(len(requests))
                return orig(vid, requests, remote_read)

            store.read_ec_needles_batch = spying
            async with aiohttp.ClientSession() as sess:

                async def read(fid):
                    async with sess.get(f"http://{vs.url}/{fid}") as r:
                        assert r.status == 200
                        return await r.read()

                for _ in range(2):
                    fids = list(blobs) * 4
                    results = await asyncio.gather(*(read(f) for f in fids))
                    for f, got in zip(fids, results):
                        assert got == blobs[f]
            assert max(seen_widths) > 1, (
                f"burst never coalesced: widths={seen_widths}"
            )
        finally:
            await cluster.stop()

    run(go())
