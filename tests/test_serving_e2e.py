"""Integrated degraded-read serving e2e: HTTP reads through the volume
server's continuous-batching EcReadDispatcher (seaweedfs_tpu/serving/)
-> Store.read_ec_needles_batch -> EcVolume resident cache -> batched
reconstruct calls, with two shards destroyed so every read MUST
reconstruct.

This is the CI-scaled promotion of the round-4 hardware drive
(experiments/r4_serving_e2e.py): same cluster wiring, same
encode/mount/pin/degrade sequence, byte-exactness asserted for
sequential reads, coalesced concurrent bursts, and the no-cache native
path — on the CPU backend (tests/conftest.py forces JAX cpu; the device
cache runs the XLA fallback kernels).  bench.py's serving sweep runs the
same path on the real TPU and publishes the measured numbers.

Reference path being matched: weed/storage/store_ec.go:136-393.
"""
import asyncio

import aiohttp
import pytest


def run(coro):
    return asyncio.run(coro)


async def _build_degraded_cluster(
    tmp_path, n_blobs=10, device_cache=True, drop_shards=(0, 11)
):
    """Cluster with one volume EC-encoded, mounted, and `drop_shards`
    destroyed; returns (cluster, vs, blobs dict fid->bytes).  Thin CI
    wrapper over bench.build_degraded_cluster — ONE implementation of
    the degrade choreography shared with the benchmark, so the measured
    path and the tested path cannot drift."""
    from bench import build_degraded_cluster

    cluster, vs, blobs, _vid = await build_degraded_cluster(
        str(tmp_path),
        n_blobs=n_blobs,
        device_cache=device_cache,
        cache_budget=1 << 30,
        # no pre-warm in CI: the XLA-fallback kernels compile in
        # milliseconds at first use, and the full warm plan (every count
        # bucket x size) would dominate the test's runtime
        warm_sizes=(),
        drop_shards=drop_shards,
    )
    return cluster, vs, blobs


@pytest.mark.parametrize("device_cache", [True, False])
def test_degraded_http_serving_byte_exact(tmp_path, device_cache):
    """Every blob reads back byte-exact over plain HTTP with two shards
    destroyed — through the batcher + resident cache when enabled, and
    through the per-read native reconstruct path when not."""

    async def go():
        cluster, vs, blobs = await _build_degraded_cluster(
            tmp_path, device_cache=device_cache
        )
        try:
            async with aiohttp.ClientSession() as sess:

                async def read(fid):
                    async with sess.get(f"http://{vs.url}/{fid}") as r:
                        assert r.status == 200, (fid, r.status)
                        return await r.read()

                # sequential correctness pass
                for fid, want in blobs.items():
                    got = await read(fid)
                    assert got == want, f"{fid}: degraded read corrupt"

                # concurrent burst: the batcher coalesces (device-cache
                # mode) or fans out per-read (native mode); both must
                # stay byte-exact under concurrency
                fids = list(blobs) * 3
                results = await asyncio.gather(*(read(f) for f in fids))
                for f, got in zip(fids, results):
                    assert got == blobs[f]

                # missing needle still 404s cleanly through the batcher
                bad_fid = next(iter(blobs)).split(",")[0] + ",ffffffffffffffff"
                async with sess.get(f"http://{vs.url}/{bad_fid}") as r:
                    assert r.status == 404
        finally:
            await cluster.stop()

    run(go())


def test_degraded_serving_batcher_coalesces(tmp_path):
    """The concurrent burst actually rides the batch path: after the
    burst, the dispatcher has seen multi-needle batches (not 1-by-1),
    repeated bursts return stable results (compile caches warm), and the
    new serving series are scrapeable from the live /metrics endpoint."""

    async def go():
        cluster, vs, blobs = await _build_degraded_cluster(
            tmp_path, n_blobs=8, device_cache=True
        )
        try:
            seen_widths = []
            store = vs.store
            orig = store.read_ec_needles_batch

            def spying(vid, requests, remote_read=None, zero_copy=False):
                seen_widths.append(len(requests))
                return orig(vid, requests, remote_read, zero_copy)

            store.read_ec_needles_batch = spying
            async with aiohttp.ClientSession() as sess:

                async def read(fid):
                    async with sess.get(f"http://{vs.url}/{fid}") as r:
                        assert r.status == 200
                        return await r.read()

                for _ in range(2):
                    fids = list(blobs) * 4
                    results = await asyncio.gather(*(read(f) for f in fids))
                    for f, got in zip(fids, results):
                        assert got == blobs[f]

                # the batching decisions must be dashboard-visible: scrape
                # the real /metrics endpoint for the new serving series
                async with sess.get(f"http://{vs.url}/metrics") as r:
                    assert r.status == 200
                    text = await r.text()
            assert max(seen_widths) > 1, (
                f"burst never coalesced: widths={seen_widths}"
            )
            for series in (
                "SeaweedFS_volumeServer_ec_batch_size_bucket",
                "SeaweedFS_volumeServer_ec_batch_queue_wait_seconds_bucket",
                "SeaweedFS_volumeServer_ec_batch_inflight",
                "SeaweedFS_volumeServer_ec_batch_fallback_total",
                'SeaweedFS_volumeServer_ec_read_route_total{route="batched"}',
            ):
                assert series in text, f"missing metrics series: {series}"
            # the burst rode the batched route, and it was counted
            batched_line = next(
                l for l in text.splitlines()
                if l.startswith(
                    'SeaweedFS_volumeServer_ec_read_route_total{route="batched"}'
                )
            )
            assert float(batched_line.split()[-1]) > 0
        finally:
            await cluster.stop()

    run(go())


def test_degraded_serving_batched_equals_unbatched(tmp_path):
    """Concurrency consistency self-check on the REAL path: a concurrent
    burst served through the coalescer/pipeline returns bytes identical
    to the same needles read one-by-one through the unbatched native
    reconstruct.  The baseline passes use_device=False (the dispatcher's
    shed path), so it exercises the independent CPU reconstruct — a
    kernel bug that corrupts both resident paths identically cannot
    pass."""

    async def go():
        cluster, vs, blobs = await _build_degraded_cluster(
            tmp_path, n_blobs=8, device_cache=True
        )
        try:
            from seaweedfs_tpu.storage import types as t

            async with aiohttp.ClientSession() as sess:

                async def read(fid):
                    async with sess.get(f"http://{vs.url}/{fid}") as r:
                        assert r.status == 200
                        return await r.read()

                fids = list(blobs) * 3
                batched = await asyncio.gather(*(read(f) for f in fids))
            for fid, got in zip(fids, batched):
                vid, nid, cookie = t.parse_fid(fid)
                direct = vs.store.read_ec_needle(
                    vid, nid, cookie, use_device=False
                )
                assert got == direct.data, (
                    f"{fid}: batched read differs from unbatched"
                )
        finally:
            await cluster.stop()

    run(go())
