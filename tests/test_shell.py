"""Admin shell against a live in-process cluster: the reference's
shell-command tests run algorithms on canned topology (SURVEY.md §4); here
the same commands run end-to-end over real gRPC."""
import asyncio
import io
import os

import pytest

from seaweedfs_tpu.operation import assign, submit_data, upload_data
from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.storage.ec import TOTAL_SHARDS


def run(coro):
    return asyncio.run(coro)


async def sh(env, line):
    await run_command(env, line)


async def make(tmp_path, n=3):
    cluster = LocalCluster(
        base_dir=str(tmp_path), n_volume_servers=n, pulse_seconds=1
    )
    await cluster.start()
    env = CommandEnv([cluster.master.advertise_url], out=io.StringIO())
    return cluster, env


async def fill_volume(cluster, n_blobs=10):
    """-> (vid, {fid: data}) all landing in one volume."""
    master = cluster.master.advertise_url
    a = await assign(master)
    vid = int(a.fid.split(",")[0])
    blobs = {}
    for i in range(n_blobs):
        ai = await assign(master)
        if int(ai.fid.split(",")[0]) != vid:
            continue
        data = os.urandom(700 + 97 * i)
        await upload_data(f"http://{ai.url}/{ai.fid}", data)
        blobs[ai.fid] = data
    return vid, blobs


async def read_all(cluster, blobs):
    import aiohttp

    vs = cluster.volume_servers[0]
    async with aiohttp.ClientSession() as s:
        for fid, data in blobs.items():
            async with s.get(f"http://{vs.url}/{fid}") as r:
                assert r.status == 200, fid
                assert await r.read() == data, fid


def test_help_lock_clusterps(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=2)
        try:
            await sh(env, "help")
            assert "ec.encode" in env.out.getvalue()
            with pytest.raises(RuntimeError):
                await sh(env, "volume.balance")
            await sh(env, "lock")
            await sh(env, "cluster.ps")
            assert "2" in env.out.getvalue()
            await sh(env, "unlock")
        finally:
            await cluster.stop()

    run(go())


def test_lock_is_exclusive(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=1)
        try:
            await sh(env, "lock")
            env2 = CommandEnv([cluster.master.advertise_url], out=io.StringIO())
            with pytest.raises(Exception):
                await sh(env2, "lock")
            await sh(env, "unlock")
            await sh(env2, "lock")
            await sh(env2, "unlock")
        finally:
            await cluster.stop()

    run(go())


def test_ec_encode_balance_rebuild_decode_roundtrip(tmp_path):
    """The full EC lifecycle through shell commands."""

    async def go():
        cluster, env = await make(tmp_path, n=3)
        try:
            vid, blobs = await fill_volume(cluster)
            await asyncio.sleep(1.2)  # heartbeat the volume into topology
            await sh(env, "lock")

            # encode + spread
            await sh(env, f"ec.encode -volumeId {vid}")
            await asyncio.sleep(1.2)
            locs = cluster.master.topo.lookup_ec_shards(vid)
            assert locs is not None
            holders = {
                n.url for shard_nodes in locs.locations for n in shard_nodes
            }
            assert len(holders) >= 2, "shards not spread"
            # original volume deleted everywhere
            assert not any(
                vs.store.has_volume(vid) for vs in cluster.volume_servers
            )
            await read_all(cluster, blobs)

            # destroy one server's shards on disk, then rebuild
            holders_vs = [
                vs for vs in cluster.volume_servers
                if vs.store.find_ec_volume(vid) is not None
            ]
            # lose the server with the fewest shards (must be <=4: RS(10,4)
            # tolerates at most 4 lost shards)
            victim = min(
                holders_vs, key=lambda vs: len(vs.store.find_ec_volume(vid).shards)
            )
            lost = sorted(victim.store.find_ec_volume(vid).shards)
            assert lost and len(lost) <= 4
            victim.store.destroy_ec_volume(vid)
            await asyncio.sleep(1.2)
            env.out.truncate(0)
            await sh(env, "ec.rebuild -force")
            assert f"rebuilt" in env.out.getvalue()
            await asyncio.sleep(1.2)
            locs = cluster.master.topo.lookup_ec_shards(vid)
            held = [sid for sid, ns in enumerate(locs.locations) if ns]
            assert len(held) == TOTAL_SHARDS
            await read_all(cluster, blobs)

            # balance shard counts
            await sh(env, "ec.balance -force")
            await asyncio.sleep(1.2)
            await read_all(cluster, blobs)

            # decode back to a normal volume
            await sh(env, f"ec.decode -volumeId {vid}")
            await asyncio.sleep(1.2)
            assert any(vs.store.has_volume(vid) for vs in cluster.volume_servers)
            assert all(
                vs.store.find_ec_volume(vid) is None
                for vs in cluster.volume_servers
            )
            await read_all(cluster, blobs)
            await sh(env, "unlock")
        finally:
            await cluster.stop()

    run(go())


def test_volume_list_and_balance(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=2)
        try:
            master = cluster.master.advertise_url
            for _ in range(4):
                await submit_data(master, os.urandom(500))
            await asyncio.sleep(1.2)
            await sh(env, "volume.list")
            out = env.out.getvalue()
            assert "volume id:" in out
            await sh(env, "lock")
            await sh(env, "volume.balance -force")
            await sh(env, "volume.fix.replication")
            await sh(env, "unlock")
        finally:
            await cluster.stop()

    run(go())


def test_fix_replication_restores_lost_replica(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=2)
        try:
            master = cluster.master.advertise_url
            a = await assign(master, replication="001")
            vid = int(a.fid.split(",")[0])
            data = os.urandom(2048)
            await upload_data(f"http://{a.url}/{a.fid}", data)
            await asyncio.sleep(1.2)
            # drop one replica
            victim = next(
                vs for vs in cluster.volume_servers if vs.store.has_volume(vid)
            )
            victim.store.delete_volume(vid)
            await asyncio.sleep(1.2)
            await sh(env, "lock")
            await sh(env, "volume.fix.replication -force")
            await asyncio.sleep(1.2)
            holders = [
                vs for vs in cluster.volume_servers if vs.store.has_volume(vid)
            ]
            assert len(holders) == 2
            # restored replica serves the data
            import aiohttp

            async with aiohttp.ClientSession() as s:
                for vs in holders:
                    async with s.get(f"http://{vs.url}/{a.fid}") as r:
                        assert r.status == 200 and await r.read() == data
            await sh(env, "unlock")
        finally:
            await cluster.stop()

    run(go())


def test_volume_configure_replication(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=1)
        try:
            vid, _ = await fill_volume(cluster, n_blobs=2)
            await sh(env, "lock")
            await sh(
                env,
                f"volume.configure.replication -volumeId {vid} -replication 001",
            )
            assert "replication 001" in env.out.getvalue()
            vs = cluster.volume_servers[0]
            v = vs.store.find_volume(vid)
            assert str(v.super_block.replica_placement) == "001"
            # persisted: survives a reload from disk
            from seaweedfs_tpu.storage.super_block import (
                SUPER_BLOCK_SIZE,
                SuperBlock,
            )

            def _read_sb():
                with open(v.dat_path, "rb") as f:
                    return f.read(SUPER_BLOCK_SIZE)

            sb = SuperBlock.from_bytes(await asyncio.to_thread(_read_sb))
            assert str(sb.replica_placement) == "001"
        finally:
            await cluster.stop()

    run(go())
