"""fs.* shell commands against a live cluster (reference:
weed/shell/command_fs_*.go)."""
import asyncio
import io

import aiohttp
import pytest

from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.shell import CommandEnv, run_command


def test_fs_commands(tmp_path):
    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, with_filer=True
        )
        await cluster.start()
        try:
            env = CommandEnv([cluster.master.advertise_url], out=io.StringIO())
            # wait for the filer to register
            deadline = asyncio.get_event_loop().time() + 10
            while True:
                try:
                    await env.find_filer()
                    break
                except RuntimeError:
                    if asyncio.get_event_loop().time() > deadline:
                        pytest.fail("filer never registered with the master")
                    await asyncio.sleep(0.1)

            base = f"http://{cluster.filer.url}"
            async with aiohttp.ClientSession() as s:
                await s.put(base + "/docs/a.txt", data=b"alpha file")
                await s.put(base + "/docs/sub/b.bin", data=b"x" * 2048)

            await run_command(env, "fs.ls /docs")
            out = env.out.getvalue()
            assert "a.txt" in out and "sub/" in out

            await run_command(env, "fs.ls -l /docs")
            assert "2.0KB" in env.out.getvalue() or "10B" in env.out.getvalue()

            await run_command(env, "fs.cat /docs/a.txt")
            assert "alpha file" in env.out.getvalue()

            await run_command(env, "fs.du /docs")
            assert "2 files, 1 dirs" in env.out.getvalue()

            await run_command(env, "fs.mkdir /new/deep/dir")
            # mkdir must refuse to pave over a file
            await run_command(env, "fs.mkdir /docs/sub/b.bin")
            assert "a file is in the way" in env.out.getvalue()
            await run_command(env, "fs.cat /docs/sub/b.bin")  # data intact
            # rm of a missing path says so
            await run_command(env, "fs.rm /no/such/thing")
            assert "no such file" in env.out.getvalue()
            await run_command(env, "fs.ls /new/deep")
            assert "dir/" in env.out.getvalue()

            await run_command(env, "fs.mv /docs/a.txt /new/renamed.txt")
            await run_command(env, "fs.cat /new/renamed.txt")
            assert env.out.getvalue().count("alpha file") == 2

            # metadata save -> metadata-only wipe -> load round trip
            meta = str(tmp_path / "meta.bin")
            await run_command(env, f"fs.meta.save -o {meta} /docs")
            assert "saved" in env.out.getvalue()
            from seaweedfs_tpu.pb import filer_pb2

            stub = env.filer_stub(await env.find_filer())
            await stub.DeleteEntry(
                filer_pb2.DeleteEntryRequest(
                    directory="/", name="docs", is_delete_data=False,
                    is_recursive=True, ignore_recursive_error=True,
                )
            )
            await run_command(env, f"fs.meta.load -i {meta}")
            assert "restored" in env.out.getvalue()
            await run_command(env, "fs.cat /docs/sub/b.bin")
            assert "xxxx" in env.out.getvalue(), "chunks resolve after reload"

            await run_command(env, "fs.rm /new/renamed.txt")
            async with aiohttp.ClientSession() as s:
                async with s.get(base + "/new/renamed.txt") as r:
                    assert r.status == 404
            # non-recursive rm of a non-empty dir fails cleanly
            await run_command(env, "fs.rm /docs")
            assert "fs.rm /docs:" in env.out.getvalue()
            await run_command(env, "fs.rm -r /docs")
            async with aiohttp.ClientSession() as s:
                async with s.get(base + "/docs/sub/b.bin") as r:
                    assert r.status == 404
        finally:
            await cluster.stop()

    asyncio.run(go())
