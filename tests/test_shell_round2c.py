"""Round-2 shell tail: fs.cd/pwd/tree/meta.cat/verify/configure,
mount.configure, mq.topic.list, remote.meta.sync, cluster.raft.*,
s3.* admin commands — live in-process clusters throughout."""
import asyncio
import io
import json
import os

import aiohttp
import pytest

from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.shell import CommandEnv, run_command


def run(coro):
    return asyncio.run(coro)


async def sh(env, line):
    await run_command(env, line)


async def make(tmp_path, **kw):
    cluster = LocalCluster(
        base_dir=str(tmp_path), n_volume_servers=1, pulse_seconds=1,
        with_filer=True, **kw
    )
    await cluster.start()
    env = CommandEnv([cluster.master.advertise_url], out=io.StringIO())
    await env.acquire_lock()
    return cluster, env


async def put(cluster, path, data: bytes):
    async with aiohttp.ClientSession() as s:
        async with s.put(
            f"http://{cluster.filer.url}{path}", data=data
        ) as r:
            assert r.status in (200, 201), await r.text()


def test_fs_cd_pwd_tree_meta_cat(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        try:
            await put(cluster, "/a/b/file.txt", b"hello")
            await sh(env, "fs.pwd")
            assert env.out.getvalue().strip() == "/"
            await sh(env, "fs.cd /a")
            await sh(env, "fs.pwd")
            assert env.out.getvalue().splitlines()[-1] == "/a"
            # relative listing from cwd
            env.out = io.StringIO()
            await sh(env, "fs.ls b")
            assert "file.txt" in env.out.getvalue()
            await sh(env, "fs.cd ..")
            await sh(env, "fs.pwd")
            assert env.out.getvalue().splitlines()[-1] == "/"
            env.out = io.StringIO()
            await sh(env, "fs.cd /a/nonexistent")
            assert "no such directory" in env.out.getvalue()
            env.out = io.StringIO()
            await sh(env, "fs.tree /a")
            out = env.out.getvalue()
            assert "b/" in out and "file.txt" in out
            assert "1 directories, 1 files" in out
            env.out = io.StringIO()
            await sh(env, "fs.meta.cat /a/b/file.txt")
            assert "file.txt" in env.out.getvalue()
        finally:
            await cluster.stop()

    run(go())


def test_fs_verify(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        try:
            await put(cluster, "/v/big.bin", os.urandom(256 * 1024))
            env.out = io.StringIO()
            await sh(env, "fs.verify /v")
            out = env.out.getvalue()
            assert "0 broken" in out and "verified" in out
        finally:
            await cluster.stop()

    run(go())


def test_fs_configure_rules_apply(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        try:
            await sh(
                env,
                "fs.configure -locationPrefix /special/ -collection vip -apply",
            )
            assert "saved" in env.out.getvalue()
            # a write under the prefix lands in the 'vip' collection
            await put(cluster, "/special/x.bin", os.urandom(8192))
            for _ in range(40):
                nodes, _ = await env.collect_topology()
                cols = {v["collection"] for n in nodes for v in n.volumes}
                if "vip" in cols:
                    break
                await asyncio.sleep(0.25)
            assert "vip" in cols
            # read-only prefix rejects writes
            await sh(
                env,
                "fs.configure -locationPrefix /frozen/ -readOnly -apply",
            )
            await asyncio.sleep(2.1)  # conf cache TTL
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    f"http://{cluster.filer.url}/frozen/no.bin", data=b"x"
                ) as r:
                    assert r.status == 403
            # delete the rule
            env.out = io.StringIO()
            await sh(
                env,
                "fs.configure -locationPrefix /frozen/ -delete -apply",
            )
            assert "/frozen/" not in env.out.getvalue().split("saved")[0]
        finally:
            await cluster.stop()

    run(go())


def test_mount_configure_quota(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        try:
            await put(cluster, "/mnt/data/f.txt", b"x")
            await sh(env, "mount.configure -dir /mnt/data -quotaMB 100")
            assert "quota 100 MB" in env.out.getvalue()
            from seaweedfs_tpu.pb import filer_pb2

            stub = env.filer_stub(await env.find_filer())
            resp = await stub.LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory="/mnt", name="data"
                )
            )
            assert resp.entry.extended["mount.quota_mb"] == b"100"
            await sh(env, "mount.configure -dir /mnt/data -quotaMB 0")
            resp = await stub.LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory="/mnt", name="data"
                )
            )
            assert "mount.quota_mb" not in resp.entry.extended
        finally:
            await cluster.stop()

    run(go())


def test_mq_topic_list(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        broker = None
        try:
            from seaweedfs_tpu.mq import MessageQueueBroker, MqClient

            broker = MessageQueueBroker(
                filer_address=cluster.filer.url,
                filer_grpc_address=f"{cluster.filer.ip}:{cluster.filer.grpc_port}",
                port=0,
                masters=[cluster.master.advertise_url],
            )
            await broker.start()
            c = MqClient(broker.grpc_url)
            await c.configure_topic(MqClient.topic("events"), 3)
            # wait for the broker to appear in the cluster registry
            for _ in range(40):
                try:
                    env.out = io.StringIO()
                    await sh(env, "mq.topic.list")
                    break
                except RuntimeError:
                    await asyncio.sleep(0.25)
            out = env.out.getvalue()
            assert "default/events" in out and "partitions=3" in out
        finally:
            if broker is not None:
                await broker.stop()
            await cluster.stop()

    run(go())


def test_remote_meta_sync(tmp_path):
    async def go():
        backing = tmp_path / "remote-store"
        backing.mkdir()
        (backing / "one.txt").write_bytes(b"1")
        cluster, env = await make(tmp_path / "cluster")
        try:
            await sh(
                env, f"remote.configure -name local.r1 -dir {backing}"
            )
            await sh(env, "remote.mount -dir /m -remote local.r1")
            env.out = io.StringIO()
            await sh(env, "fs.ls /m")
            assert "one.txt" in env.out.getvalue()
            # remote gains and loses files
            (backing / "two.txt").write_bytes(b"22")
            (backing / "one.txt").unlink()
            env.out = io.StringIO()
            await sh(env, "remote.meta.sync -dir /m")
            assert "+1" in env.out.getvalue()
            assert "-1" in env.out.getvalue()
            env.out = io.StringIO()
            await sh(env, "fs.ls /m")
            out = env.out.getvalue()
            assert "two.txt" in out and "one.txt" not in out
        finally:
            await cluster.stop()

    run(go())


def test_cluster_raft_ps_single_master(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        try:
            await sh(env, "cluster.raft.ps")
            out = env.out.getvalue()
            assert "leader" in out
        finally:
            await cluster.stop()

    run(go())


def test_s3_bucket_lifecycle_and_quota(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        try:
            await sh(env, "s3.bucket.create -name demo")
            env.out = io.StringIO()
            await sh(env, "s3.bucket.list")
            assert "demo" in env.out.getvalue()
            await sh(env, "s3.bucket.quota -name demo -sizeMB 1")
            # over-fill the 1MB quota
            await put(cluster, "/buckets/demo/big.bin", os.urandom(2 * 1024 * 1024))
            env.out = io.StringIO()
            await sh(env, "s3.bucket.quota.check -apply")
            assert "OVER QUOTA" in env.out.getvalue()
            await asyncio.sleep(2.1)  # filer.conf cache TTL
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    f"http://{cluster.filer.url}/buckets/demo/more.bin",
                    data=b"x",
                ) as r:
                    assert r.status == 403  # bucket frozen
            # shrink below quota -> rule lifted
            await sh(env, "fs.rm /buckets/demo/big.bin")
            env.out = io.StringIO()
            await sh(env, "s3.bucket.quota.check -apply")
            await asyncio.sleep(2.1)
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    f"http://{cluster.filer.url}/buckets/demo/more.bin",
                    data=b"x",
                ) as r:
                    assert r.status in (200, 201)
            await sh(env, "s3.bucket.delete -name demo")
            env.out = io.StringIO()
            await sh(env, "s3.bucket.list")
            assert "demo" not in env.out.getvalue()
        finally:
            await cluster.stop()

    run(go())


def test_s3_configure_and_circuitbreaker(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        try:
            await sh(
                env,
                "s3.configure -user alice -access_key AK1 -secret_key SK1 "
                "-actions Read,Write -apply",
            )
            env.out = io.StringIO()
            await sh(env, "s3.configure")
            cfg = json.loads(env.out.getvalue())
            assert cfg["identities"][0]["name"] == "alice"
            assert cfg["identities"][0]["credentials"][0]["accessKey"] == "AK1"

            await sh(
                env,
                "s3.circuitbreaker -global -actions Read -type Count "
                "-values 100 -apply",
            )
            env.out = io.StringIO()
            await sh(env, "s3.circuitbreaker")
            cb = json.loads(env.out.getvalue())
            assert cb["global"]["actions"]["Read:Count"] == 100
        finally:
            await cluster.stop()

    run(go())


def test_s3_clean_uploads(tmp_path):
    async def go():
        cluster, env = await make(tmp_path)
        try:
            from seaweedfs_tpu.pb import filer_pb2
            from seaweedfs_tpu.s3api.server import UPLOADS_DIR

            await sh(env, "s3.bucket.create -name up")
            stub = env.filer_stub(await env.find_filer())
            # fabricate an old dangling multipart upload
            await stub.CreateEntry(
                filer_pb2.CreateEntryRequest(
                    directory=f"/buckets/up/{UPLOADS_DIR}",
                    entry=filer_pb2.Entry(
                        name="deadbeef", is_directory=True,
                        attributes=filer_pb2.FuseAttributes(crtime=1000),
                    ),
                )
            )
            env.out = io.StringIO()
            await sh(env, "s3.clean.uploads -timeAgo 1h")
            assert "cleaned 1" in env.out.getvalue()
        finally:
            await cluster.stop()

    run(go())


def test_cluster_raft_membership(tmp_path):
    """cluster.raft.add/remove drive live raft membership change."""
    from tests.test_master_ha import free_ports, wait_leader

    async def go():
        from seaweedfs_tpu.server.master import MasterServer

        # explicit grpc ports in the peer urls: the +10000 convention can
        # collide with another allocated port on a busy test host, and a
        # rebound grpc port would silently break flag-form peer dialing
        ports = free_ports(6)
        http, grpc_ports = ports[:3], ports[3:]
        urls = [
            f"127.0.0.1:{p}.{g}" for p, g in zip(http, grpc_ports)
        ]
        # start a 2-node cluster; the third master starts with full peer
        # list but isn't a member until cluster.raft.add
        masters = []
        for i in range(2):
            m = MasterServer(
                port=http[i], grpc_port=grpc_ports[i], peers=list(urls[:2]),
                meta_dir=str(tmp_path / f"m{i}"), pulse_seconds=1,
            )
            masters.append(m)
        await asyncio.gather(*(m.start() for m in masters))
        extra = MasterServer(
            port=http[2], grpc_port=grpc_ports[2], peers=list(urls),
            meta_dir=str(tmp_path / "m2"), pulse_seconds=1,
            raft_join=True,  # non-voter until cluster.raft.add
        )
        await extra.start()
        try:
            leader = await wait_leader(masters)
            env = CommandEnv([leader.advertise_url], out=io.StringIO())
            await env.acquire_lock()
            await sh(env, "cluster.raft.ps")
            before = env.out.getvalue()
            assert extra.raft.id not in before  # extra not a member yet

            raft_id = extra.raft.id
            assert not extra.raft.voter
            await sh(env, f"cluster.raft.add -id {raft_id}")
            env.out = io.StringIO()
            await sh(env, "cluster.raft.ps")
            assert raft_id in env.out.getvalue()
            assert raft_id in leader.raft.peers
            # the joiner receives the config entry via replication and is
            # promoted to voter with the full member list
            for _ in range(40):
                if extra.raft.voter and len(extra.raft.peers) == 2:
                    break
                await asyncio.sleep(0.25)
            assert extra.raft.voter
            # id forms may mix flag-form and advertise-form strings for
            # the same node; compare canonically through the dial mapping
            from seaweedfs_tpu.pb import server_address

            canon = server_address.grpc_address
            assert {canon(p) for p in extra.raft.peers} == {
                canon(m.raft.id) for m in masters
            }

            await sh(env, f"cluster.raft.remove -id {raft_id}")
            env.out = io.StringIO()
            await sh(env, "cluster.raft.ps")
            assert raft_id not in env.out.getvalue()
            assert all(
                canon(p) != canon(raft_id) for p in leader.raft.peers
            )
        finally:
            await asyncio.gather(
                *(m.stop() for m in [*masters, extra]),
                return_exceptions=True,
            )

    run(go())


def test_s3_circuitbreaker_enforced(tmp_path):
    """A Write:Count limit of 0 rejects every write with 503 SlowDown;
    removing the rule restores service."""

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, pulse_seconds=1,
            with_s3=True,
        )
        await cluster.start()
        env = CommandEnv([cluster.master.advertise_url], out=io.StringIO())
        await env.acquire_lock()
        try:
            s3 = f"http://{cluster.s3.url}"
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{s3}/cbbucket") as r:
                    assert r.status == 200
            await sh(
                env,
                "s3.circuitbreaker -global -actions Write -type Count "
                "-values 0 -apply",
            )
            await cluster.s3._load_cb_from_filer()
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{s3}/cbbucket/x.bin", data=b"x") as r:
                    assert r.status == 503
                    assert "SlowDown" in await r.text()
                # reads unaffected
                async with s.get(f"{s3}/cbbucket?list-type=2") as r:
                    assert r.status == 200
            await sh(
                env,
                "s3.circuitbreaker -global -actions Write -type Count "
                "-delete -apply",
            )
            await cluster.s3._load_cb_from_filer()
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{s3}/cbbucket/x.bin", data=b"x") as r:
                    assert r.status == 200
        finally:
            await cluster.stop()

    run(go())


def test_fs_meta_notify_and_change_volume_id(tmp_path):
    async def go():
        from seaweedfs_tpu.pb import filer_pb2
        from seaweedfs_tpu.replication.notification import FileQueueNotifier

        cluster, env = await make(tmp_path)
        try:
            await put(cluster, "/n/a.txt", os.urandom(4096))
            await put(cluster, "/n/sub/b.txt", b"bb")
            spool = str(tmp_path / "spool.bin")
            await sh(env, f"fs.meta.notify -spool {spool} /n")
            assert "notified" in env.out.getvalue()
            events = FileQueueNotifier.read_all(spool)
            names = {e.new_entry.name for _, e in events}
            assert {"a.txt", "sub", "b.txt"} <= names

            # volume id rewrite in chunk metadata
            stub = env.filer_stub(await env.find_filer())
            resp = await stub.LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(directory="/n", name="a.txt")
            )
            old_vid = int(resp.entry.chunks[0].file_id.partition(",")[0])
            new_vid = old_vid + 500
            env.out = io.StringIO()
            await sh(
                env,
                f"fs.meta.change.volume.id -from {old_vid} -to {new_vid} -force /n",
            )
            assert "rewritten" in env.out.getvalue()
            resp = await stub.LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(directory="/n", name="a.txt")
            )
            assert all(
                c.file_id.startswith(f"{new_vid},") for c in resp.entry.chunks
            )
        finally:
            await cluster.stop()

    run(go())


def test_remote_mount_buckets(tmp_path):
    async def go():
        backing = tmp_path / "store"
        (backing / "alpha").mkdir(parents=True)
        (backing / "beta").mkdir()
        (backing / "alpha" / "x.txt").write_bytes(b"ax")
        (backing / "beta" / "y.txt").write_bytes(b"by")
        cluster, env = await make(tmp_path / "cluster")
        try:
            await sh(env, f"remote.configure -name local.rb -dir {backing}")
            env.out = io.StringIO()
            await sh(env, "remote.mount.buckets -remote local.rb")
            assert "mounted 2 remote buckets" in env.out.getvalue()
            env.out = io.StringIO()
            await sh(env, "fs.ls /buckets/alpha")
            assert "x.txt" in env.out.getvalue()
            env.out = io.StringIO()
            await sh(env, "fs.ls /buckets/beta")
            assert "y.txt" in env.out.getvalue()
            # a prefixed -remote enumerates buckets UNDER the prefix
            (backing / "deep" / "gamma").mkdir(parents=True)
            (backing / "deep" / "gamma" / "z.txt").write_bytes(b"gz")
            env.out = io.StringIO()
            await sh(env, "remote.mount.buckets -remote local.rb/deep")
            assert "mounted 1 remote buckets" in env.out.getvalue()
            env.out = io.StringIO()
            await sh(env, "fs.ls /buckets/gamma")
            assert "z.txt" in env.out.getvalue()
        finally:
            await cluster.stop()

    run(go())
