"""Round-2 shell long tail: volume.copy, volume.check.disk,
volume.delete.empty, volume.server.evacuate/leave, volume.tier.move,
volume.vacuum.disable/enable — each against a live in-process cluster
(the reference's command_volume_*.go behaviors, SURVEY.md §4)."""
import asyncio
import io
import os

import aiohttp
import pytest

from seaweedfs_tpu.operation import assign, upload_data
from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.storage.types import parse_fid


def run(coro):
    return asyncio.run(coro)


async def sh(env, line):
    await run_command(env, line)


async def make(tmp_path, n=2, **kw):
    cluster = LocalCluster(
        base_dir=str(tmp_path), n_volume_servers=n, pulse_seconds=1, **kw
    )
    await cluster.start()
    env = CommandEnv([cluster.master.advertise_url], out=io.StringIO())
    await env.acquire_lock()
    return cluster, env


async def fill_volume(cluster, n_blobs=6):
    master = cluster.master.advertise_url
    a = await assign(master)
    vid = int(a.fid.split(",")[0])
    data = os.urandom(512)
    await upload_data(f"http://{a.url}/{a.fid}", data)
    blobs = {a.fid: data}
    for i in range(n_blobs - 1):
        ai = await assign(master)
        if int(ai.fid.split(",")[0]) != vid:
            continue
        data = os.urandom(500 + 31 * i)
        await upload_data(f"http://{ai.url}/{ai.fid}", data)
        blobs[ai.fid] = data
    return vid, blobs


def holders_of(cluster, vid):
    return [
        vs for vs in cluster.volume_servers
        if vs.store.find_volume(vid) is not None
    ]


def test_volume_copy_and_check_disk_sync(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=2)
        try:
            vid, blobs = await fill_volume(cluster)
            src = holders_of(cluster, vid)[0]
            dst = next(
                vs for vs in cluster.volume_servers if vs is not src
            )
            await sh(
                env,
                f"volume.copy -volumeId {vid} "
                f"-source {src.grpc_url} -target {dst.grpc_url}",
            )
            assert dst.store.find_volume(vid) is not None
            # let the new replica reach the master's topology
            for _ in range(40):
                nodes, _ = await env.collect_topology()
                if sum(
                    1 for n in nodes for v in n.volumes if v["id"] == vid
                ) == 2:
                    break
                await asyncio.sleep(0.25)

            # diverge: append one needle straight to src only
            async with aiohttp.ClientSession() as s:
                fid = f"{vid},999deadbeef1"
                async with s.post(
                    f"http://{src.url}/{fid}",
                    data={"file": b"only-on-src"},
                ) as r:
                    assert r.status in (200, 201), await r.text()

            env.out = io.StringIO()
            await sh(env, f"volume.check.disk -volumeId {vid}")
            assert "missing from" in env.out.getvalue()

            await sh(env, f"volume.check.disk -volumeId {vid} -force")
            # dst now serves the needle locally
            _, nid, _ = parse_fid(fid)
            n = dst.store.read_needle(vid, nid)
            assert n.data == b"only-on-src"

            env.out = io.StringIO()
            await sh(env, f"volume.check.disk -volumeId {vid}")
            assert "0 needles" in env.out.getvalue()

            # tombstones propagate too: delete on dst only, check.disk must
            # delete on src rather than resurrect from it
            async with aiohttp.ClientSession() as s:
                async with s.delete(f"http://{dst.url}/{fid}") as r:
                    assert r.status in (200, 202, 204), await r.text()
            await sh(env, f"volume.check.disk -volumeId {vid} -force")
            with pytest.raises(Exception):
                src.store.read_needle(vid, nid)

            # ...but a delete-then-RE-ADD beats a stale tombstone: the
            # re-written needle must be synced, not destroyed
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://{src.url}/{fid}", data={"file": b"v2-after-del"}
                ) as r:
                    assert r.status in (200, 201)
            await sh(env, f"volume.check.disk -volumeId {vid} -force")
            assert src.store.read_needle(vid, nid).data == b"v2-after-del"
            assert dst.store.read_needle(vid, nid).data == b"v2-after-del"
        finally:
            await cluster.stop()

    run(go())


def test_volume_delete_empty(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=1)
        try:
            vid, blobs = await fill_volume(cluster, n_blobs=3)
            # grow a second, never-written volume
            from seaweedfs_tpu.pb import server_address

            master_http = server_address.http_address(
                cluster.master.advertise_url
            )
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{master_http}/vol/grow?count=1"
                ) as r:
                    assert r.status == 200
            # wait until the master's view shows BOTH the new empty volume
            # and a non-zero file_count on the filled one (full heartbeats
            # are periodic, so the counters lag the writes)
            for _ in range(60):
                nodes, _ = await env.collect_topology()
                vols = {v["id"]: v for n in nodes for v in n.volumes}
                if len(vols) >= 2 and vols.get(vid, {}).get("file_count", 0) > 0:
                    break
                await asyncio.sleep(0.25)
            assert vols[vid]["file_count"] > 0

            await sh(env, "volume.delete.empty -quietFor 0s -force")
            for _ in range(40):  # deltas reach the master on the next pulse
                nodes, _ = await env.collect_topology()
                left = {v["id"] for n in nodes for v in n.volumes}
                if left == {vid}:
                    break
                await asyncio.sleep(0.25)
            assert left == {vid}  # only the filled volume survives
        finally:
            await cluster.stop()

    run(go())


def test_vacuum_disable_enable(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=1)
        try:
            await sh(env, "volume.vacuum.disable")
            assert cluster.master.vacuum_disabled
            assert await cluster.master._vacuum_pass(0.0) == 0
            await sh(env, "volume.vacuum.enable")
            assert not cluster.master.vacuum_disabled
        finally:
            await cluster.stop()

    run(go())


def test_volume_server_evacuate(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=3)
        try:
            vid, blobs = await fill_volume(cluster)
            victim = holders_of(cluster, vid)[0]
            env.out = io.StringIO()
            await sh(env, f"volume.server.evacuate -node {victim.url} -force")
            assert "move volume" in env.out.getvalue()
            assert victim.store.find_volume(vid) is None
            others = holders_of(cluster, vid)
            assert others, "volume must land somewhere else"
            # data survives the move
            n0 = others[0].store
            for fid, data in blobs.items():
                _, nid, _ = parse_fid(fid)
                assert n0.read_needle(vid, nid).data == data
        finally:
            await cluster.stop()

    run(go())


def test_volume_server_leave(tmp_path):
    async def go():
        cluster, env = await make(tmp_path, n=2)
        try:
            victim = cluster.volume_servers[1]
            await sh(env, f"volume.server.leave -node {victim.grpc_url}")
            for _ in range(40):
                nodes, _ = await env.collect_topology()
                if len(nodes) == 1:
                    break
                await asyncio.sleep(0.25)
            assert len(nodes) == 1
        finally:
            await cluster.stop()

    run(go())


def test_volume_tier_move(tmp_path):
    async def go():
        cluster, env = await make(
            tmp_path, n=2, dirs_per_server=2, disk_types=["hdd", "ssd"]
        )
        try:
            vid, blobs = await fill_volume(cluster)
            src = holders_of(cluster, vid)[0]

            env.out = io.StringIO()
            await sh(env, "volume.tier.move -fromDiskType hdd -toDiskType ssd -fullPercent 0")
            assert f"move volume {vid}" in env.out.getvalue()

            await sh(
                env,
                "volume.tier.move -fromDiskType hdd -toDiskType ssd -fullPercent 0 -force",
            )
            assert src.store.find_volume(vid) is None
            dst = holders_of(cluster, vid)
            assert len(dst) == 1
            loc = dst[0].store.location_of_volume(vid)
            assert loc.disk_type == "ssd"
            # blobs still readable from the ssd replica
            for fid, data in blobs.items():
                _, nid, _ = parse_fid(fid)
                assert dst[0].store.read_needle(vid, nid).data == data
        finally:
            await cluster.stop()

    run(go())
