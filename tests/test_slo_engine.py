"""Multi-window burn-rate math, table-driven (ISSUE r17 satellite):
fast-window trip needs the slow-window confirm, recovery resets the
budget, and +Inf overflow folds from r08 digest merges must not poison
the latency estimates.  The engine is driven with a pinned clock and a
stub telemetry plane so every window boundary is exact."""
from __future__ import annotations

import math

import pytest

from seaweedfs_tpu.obs import slo as slo_mod
from seaweedfs_tpu.obs.slo import (
    BurnWindow,
    SloConfig,
    SloEngine,
    _bad_from_buckets,
)
from seaweedfs_tpu.stats.metrics import STAGE_SECONDS_BUCKETS

N_BUCKETS = len(STAGE_SECONDS_BUCKETS) + 1


class StubTelemetry:
    """Just the three accessors the engine samples."""

    def __init__(self):
        self.buckets: dict[str, list[float]] = {}
        self.reads = 0
        self.sheds = 0
        self.breakers = 0

    def stage_buckets(self, stage):
        b = self.buckets.get(stage)
        return list(b) if b is not None else None

    def read_shed_totals(self):
        return self.reads, self.sheds

    def breakers_open(self):
        return self.breakers


class StubRepair:
    def __init__(self):
        self.unhealthy_s: float | None = None

    def unhealthy_for(self):
        return self.unhealthy_s


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _bucket_at(seconds: float) -> int:
    """Index of the ladder bucket containing `seconds`."""
    for i, edge in enumerate(STAGE_SECONDS_BUCKETS):
        if seconds <= edge:
            return i
    return N_BUCKETS - 1


# ----------------------------------------------------------- BurnWindow


def test_burn_window_table():
    # (samples as (t, bad, total), window_s, budget, now, expect_burn)
    cases = [
        # no traffic -> no burn
        ([], 60, 0.01, 100.0, 0.0),
        # 1% bad at a 1% budget = burning exactly the budgeted rate
        ([(95.0, 1, 100)], 60, 0.01, 100.0, 1.0),
        # 5% bad at 1% budget = 5x
        ([(95.0, 5, 100)], 60, 0.01, 100.0, 5.0),
        # sample outside the window does not count
        ([(30.0, 50, 100), (95.0, 0, 100)], 60, 0.01, 100.0, 0.0),
        # split across samples inside the window
        ([(70.0, 1, 100), (95.0, 1, 100)], 60, 0.01, 100.0, 1.0),
    ]
    for samples, window, budget, now, expect in cases:
        w = BurnWindow(retain_seconds=600)
        for t, bad, total in samples:
            w.observe(t, bad, total)
        assert w.burn(window, budget, now) == pytest.approx(expect), (
            samples, window, budget,
        )


def test_burn_window_retention_drops_old_samples():
    w = BurnWindow(retain_seconds=100)
    w.observe(0.0, 10, 10)
    w.observe(200.0, 0, 10)  # the t=0 sample is past retention
    assert w.fractions(1000, 200.0) == (0.0, 10.0)


# -------------------------------------------------- fast trip / slow confirm


def _latency_engine(fast=60.0, slow=600.0, target_ms=1.0):
    tel = StubTelemetry()
    clock = Clock()
    cfg = SloConfig(
        read_p99_ms=target_ms, fast_window_seconds=fast,
        slow_window_seconds=slow,
    )
    eng = SloEngine(cfg, tel, repair=None, clock=clock)
    tel.buckets["batch_dispatch"] = [0.0] * N_BUCKETS
    return eng, tel, clock


def _pulse(eng, tel, clock, good=0, bad=0, dt=5.0):
    """Advance one pulse: `good` observations in the fastest bucket,
    `bad` in the +Inf overflow (slower than every edge)."""
    clock.t += dt
    tel.buckets["batch_dispatch"][0] += good
    tel.buckets["batch_dispatch"][-1] += bad
    return eng.evaluate()


def test_fast_trip_needs_slow_confirm_then_fires():
    # slow window = 3 pulses of history at dt=5: a single bad pulse
    # trips the fast window immediately but the SLOW window must also
    # cross the threshold before a violation fires
    eng, tel, clock = _latency_engine(fast=5.0, slow=15.0)
    _pulse(eng, tel, clock)  # baseline snapshot (no delta yet)
    # lots of good traffic far beyond the budget: no violation
    for _ in range(3):
        assert _pulse(eng, tel, clock, good=1000) == []
    spec = eng.specs[slo_mod.READ_P99]
    assert spec.last_fast_burn == 0.0 and not spec.violating

    # one heavily-bad pulse: fast window (one pulse wide) burns hard;
    # the slow window still holds the 2 earlier good pulses, so the
    # slow burn is diluted — but 100 bad / 2100 total = 4.8% >> 1%
    # budget, so BOTH cross and the violation fires exactly once
    fired = _pulse(eng, tel, clock, good=0, bad=100)
    assert [v["slo"] for v in fired] == [slo_mod.READ_P99]
    assert spec.violating and spec.violations_total == 1
    assert spec.last_fast_burn >= spec.last_slow_burn > 1.0

    # still burning: no RE-fire while the violation holds
    assert _pulse(eng, tel, clock, bad=50) == []
    assert spec.violations_total == 1


def test_slow_window_dilution_blocks_the_fast_trip():
    # same shape, but the bad pulse is small enough that the slow
    # window's accumulated good traffic keeps slow burn under 1.0:
    # fast trips, slow does NOT confirm, nothing fires
    eng, tel, clock = _latency_engine(fast=5.0, slow=15.0)
    _pulse(eng, tel, clock)
    for _ in range(2):
        _pulse(eng, tel, clock, good=10_000)
    # 200 bad: fast window (this pulse + the boundary pulse) sees
    # 200/10200 = 2% > 1% budget; slow sees 200/20200 = 0.99% < 1%
    fired = _pulse(eng, tel, clock, good=0, bad=200)
    spec = eng.specs[slo_mod.READ_P99]
    assert fired == []
    assert spec.last_fast_burn > 1.0  # the fast window IS burning
    assert spec.last_slow_burn < 1.0  # ... but slow says blip
    assert not spec.violating


def test_recovery_resets_budget():
    eng, tel, clock = _latency_engine(fast=5.0, slow=15.0)
    _pulse(eng, tel, clock)
    _pulse(eng, tel, clock, bad=100)
    spec = eng.specs[slo_mod.READ_P99]
    assert spec.violating
    assert eng.status()["objectives"][slo_mod.READ_P99][
        "budget_remaining"
    ] == 0.0
    # good pulses age the bad sample out of both windows: the
    # violation clears and the budget refills to 1.0 on its own
    for _ in range(4):
        _pulse(eng, tel, clock, good=1000)
    assert not spec.violating
    doc = eng.status()["objectives"][slo_mod.READ_P99]
    assert doc["budget_remaining"] == 1.0
    assert doc["fast_burn"] == 0.0 and doc["slow_burn"] == 0.0
    # the historical violation count survives recovery
    assert doc["violations_total"] == 1


# ------------------------------------------------------- overflow honesty


def test_overflow_folds_do_not_poison_p99():
    """r08 digest merges fold foreign ladders into the +Inf bucket; the
    engine's windowed p99 estimate must stay finite (the last finite
    edge, flagged as overflow), never inf/NaN."""
    eng, tel, clock = _latency_engine(fast=5.0, slow=50.0)
    _pulse(eng, tel, clock)
    _pulse(eng, tel, clock, good=10, bad=10_000)  # overflow-dominated
    p99, overflow = eng._window_p99()
    assert p99 is not None and math.isfinite(p99)
    assert p99 == pytest.approx(STAGE_SECONDS_BUCKETS[-1])
    assert overflow == 10_000
    doc = eng.status()["objectives"][slo_mod.READ_P99]
    assert doc["window_p99_seconds"] == pytest.approx(
        STAGE_SECONDS_BUCKETS[-1]
    )
    assert doc["window_p99_overflow"] == 10_000


def test_bad_from_buckets_partial_and_overflow():
    deltas = [0.0] * N_BUCKETS
    # target exactly on a bucket edge: everything above is bad
    t_idx = 5
    target = STAGE_SECONDS_BUCKETS[t_idx]
    deltas[t_idx] = 100.0  # bucket ENDING at the target: all good
    deltas[t_idx + 1] = 40.0  # next bucket: all bad
    deltas[-1] = 7.0  # overflow: all bad
    bad, total = _bad_from_buckets(deltas, target)
    assert total == 147.0
    assert bad == pytest.approx(47.0)
    # target mid-bucket: linear share of that bucket counts bad
    lo, hi = STAGE_SECONDS_BUCKETS[3], STAGE_SECONDS_BUCKETS[4]
    mid = lo + 0.25 * (hi - lo)
    deltas2 = [0.0] * N_BUCKETS
    deltas2[4] = 100.0  # the (lo, hi] bucket
    bad2, total2 = _bad_from_buckets(deltas2, mid)
    assert total2 == 100.0
    assert bad2 == pytest.approx(75.0)
    # empty pulse
    assert _bad_from_buckets([0.0] * N_BUCKETS, 0.001) == (0.0, 0.0)


def test_counter_reset_clamps_negative_deltas():
    """A restarted volume server resets its cumulative read counters;
    the per-pulse delta must clamp to 0, not burn the error budget."""
    tel = StubTelemetry()
    clock = Clock()
    eng = SloEngine(
        SloConfig(error_rate_pct=1.0, fast_window_seconds=5,
                  slow_window_seconds=15),
        tel, clock=clock,
    )
    tel.reads, tel.sheds = 1000, 500
    eng.evaluate()  # baseline
    tel.reads, tel.sheds = 100, 0  # restart: counters went backwards
    clock.t += 5
    assert eng.evaluate() == []
    spec = eng.specs[slo_mod.ERROR_RATE]
    assert spec.last_fast_burn == 0.0


def test_error_rate_and_breaker_and_tth_objectives():
    tel = StubTelemetry()
    rep = StubRepair()
    clock = Clock()
    eng = SloEngine(
        SloConfig(
            error_rate_pct=1.0, breaker_open_pct=10.0,
            time_to_healthy_seconds=30.0,
            fast_window_seconds=5, slow_window_seconds=10,
        ),
        tel, repair=rep, clock=clock,
    )
    eng.evaluate()  # baselines
    # 50% sheds vs a 1% budget, breakers open, repair 60s unhealthy:
    # all three objectives burn on the next two pulses
    tel.reads, tel.sheds = 1000, 500
    tel.breakers = 2
    rep.unhealthy_s = 60.0
    clock.t += 5
    fired1 = {v["slo"] for v in eng.evaluate()}
    tel.reads, tel.sheds = 2000, 1000
    clock.t += 5
    fired2 = {v["slo"] for v in eng.evaluate()}
    assert slo_mod.ERROR_RATE in fired1 | fired2
    assert slo_mod.BREAKER_OPEN in fired1 | fired2
    assert slo_mod.TIME_TO_HEALTHY in fired1 | fired2
    # none of them is a latency SLO -> no profile capture gate
    for spec in eng.specs.values():
        assert spec.latency is False


def test_config_validation():
    with pytest.raises(ValueError):
        SloConfig(read_p99_ms=-1).validated()
    with pytest.raises(ValueError):
        # a typo'd stage must fail loudly, not arm an objective that
        # samples (0, 0) forever
        SloConfig(read_p99_ms=5, read_stage="batch_dispach").validated()
    with pytest.raises(ValueError):
        # a target past the ladder's last finite edge would count
        # IN-target reads (landing in +Inf) as violations
        SloConfig(
            read_p99_ms=STAGE_SECONDS_BUCKETS[-1] * 1e3 + 1
        ).validated()
    with pytest.raises(ValueError):
        SloConfig(error_rate_pct=101).validated()
    with pytest.raises(ValueError):
        SloConfig(fast_window_seconds=0).validated()
    with pytest.raises(ValueError):
        SloConfig(
            fast_window_seconds=60, slow_window_seconds=30
        ).validated()
    with pytest.raises(ValueError):
        SloConfig(burn_threshold=0).validated()
    # all-zero targets = engine with no specs = evaluate() no-ops
    eng = SloEngine(SloConfig(), StubTelemetry())
    assert eng.specs == {} and eng.evaluate() == []
