"""HTML status pages (reference master_server_handlers_ui.go,
volume_server_ui/, filer_ui/): `Accept: text/html` renders operator
pages on master /, volume /status, and filer directory GETs, while JSON
clients keep their existing responses.
"""
import asyncio

import aiohttp

from seaweedfs_tpu.operation import assign, upload_data
from seaweedfs_tpu.server.cluster import LocalCluster

HTML = {"Accept": "text/html"}


def run(coro):
    return asyncio.run(coro)


async def fetch(url, headers=None):
    async with aiohttp.ClientSession() as s:
        async with s.get(url, headers=headers or {}) as r:
            return r.status, r.content_type, await r.text()


def test_status_pages(tmp_path):
    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, with_filer=True,
            pulse_seconds=1,
        )
        await cluster.start()
        try:
            master = cluster.master.advertise_url
            a = await assign(master)
            await upload_data(f"http://{a.url}/{a.fid}", b"ui-test-needle")
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    f"http://{cluster.filer.url}/docs/hello.txt", data=b"hi"
                ) as r:
                    assert r.status < 300
            await asyncio.sleep(1.2)  # heartbeat: volume visible on master

            vs = cluster.volume_servers[0]
            from seaweedfs_tpu.pb import server_address

            master_http = server_address.http_address(master)

            # master: HTML for browsers, JSON dir status untouched
            status, ctype, text = await fetch(f"http://{master_http}/", HTML)
            assert status == 200 and ctype == "text/html"
            assert "Topology" in text or "Volumes" in text
            assert vs.url in text, "volume node must appear in the topology"
            status, ctype, _ = await fetch(f"http://{master_http}/dir/status")
            assert status == 200 and ctype == "application/json"

            # volume server: disks + volumes tables
            status, ctype, text = await fetch(
                f"http://{vs.url}/status", HTML
            )
            assert status == 200 and ctype == "text/html"
            assert "Disks" in text and "Volumes" in text
            assert str(a.fid.split(",")[0]) in text
            status, ctype, _ = await fetch(f"http://{vs.url}/status")
            assert ctype == "application/json"

            # filer: directory listing page with the file linked
            status, ctype, text = await fetch(
                f"http://{cluster.filer.url}/docs", HTML
            )
            assert status == 200 and ctype == "text/html"
            assert "hello.txt" in text
            status, ctype, _ = await fetch(
                f"http://{cluster.filer.url}/docs"
            )
            assert ctype == "application/json"
        finally:
            await cluster.stop()

    run(go())
