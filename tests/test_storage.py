"""Storage engine tests on real tmp files — the reference tests the volume
engine against the OS, not a fake filesystem (volume_read_test.go,
volume_write_test.go, volume_vacuum_test.go)."""
import os

import numpy as np
import pytest

from seaweedfs_tpu.storage import idx, needle_map, vacuum
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import (
    CURRENT_VERSION,
    CrcError,
    Needle,
    actual_size,
    padding_length,
)
from seaweedfs_tpu.storage.super_block import SuperBlock
from seaweedfs_tpu.storage.volume import (
    CookieMismatch,
    NotFoundError,
    Volume,
    VolumeReadOnly,
)


# --- scalar types -----------------------------------------------------------


def test_ttl_roundtrip():
    for s in ("", "5m", "3h", "2d", "1w", "6M", "1y"):
        ttl = t.TTL.parse(s)
        assert str(ttl) == s
        assert t.TTL.from_bytes(ttl.to_bytes()) == ttl
    assert t.TTL.parse("3h").minutes == 180
    with pytest.raises(ValueError):
        t.TTL.parse("7q")


def test_replica_placement():
    rp = t.ReplicaPlacement.parse("012")
    assert (rp.diff_dc, rp.diff_rack, rp.same_rack) == (0, 1, 2)
    assert rp.copy_count == 4
    assert t.ReplicaPlacement.from_byte(rp.to_byte()) == rp
    with pytest.raises(ValueError):
        t.ReplicaPlacement.parse("039")


def test_fid_roundtrip():
    fid = t.format_fid(3, 0x0163, 0x7037D6AA)
    vid, nid, cookie = t.parse_fid(fid)
    assert (vid, nid, cookie) == (3, 0x0163, 0x7037D6AA)
    with pytest.raises(ValueError):
        t.parse_fid("nonsense")
    with pytest.raises(ValueError):
        t.parse_fid("3,ab")  # too short for cookie


def test_offset_encoding():
    for off in (0, 8, 4096, 2**32):
        assert t.offset_from_bytes(t.offset_to_bytes(off)) == off


# --- needle codec -----------------------------------------------------------


def test_needle_roundtrip_v2_v3():
    for version in (2, 3):
        n = Needle(
            id=0xABCDEF,
            cookie=0x12345678,
            data=b"hello needle world",
            name=b"file.txt",
            mime=b"text/plain",
            last_modified=1_700_000_000,
            ttl=t.TTL.parse("3d"),
            pairs=b'{"k":"v"}',
        )
        buf = n.to_bytes(version)
        assert len(buf) % 8 == 0
        m = Needle.from_bytes(buf, version)
        assert m.id == n.id and m.cookie == n.cookie
        assert m.data == n.data and m.name == n.name and m.mime == n.mime
        assert m.last_modified == n.last_modified
        assert str(m.ttl) == "3d"
        assert m.pairs == n.pairs
        if version == 3:
            assert m.append_at_ns == n.append_at_ns


def test_needle_v1_roundtrip():
    n = Needle(id=7, cookie=9, data=b"v1 payload")
    buf = n.to_bytes(1)
    m = Needle.from_bytes(buf, 1)
    assert m.data == n.data


def test_needle_crc_detects_corruption():
    n = Needle(id=1, cookie=2, data=b"payload bytes here")
    buf = bytearray(n.to_bytes())
    buf[t.NEEDLE_HEADER_SIZE + 4 + 3] ^= 0xFF  # flip a data byte
    with pytest.raises(CrcError):
        Needle.from_bytes(bytes(buf))


def test_padding_quirk_matches_reference():
    """PaddingLength returns 8 - (x % 8), i.e. 8 (not 0) when aligned —
    reproduced for byte compatibility (needle_read.go:198-204)."""
    for size in range(0, 64):
        pad = padding_length(size, 3)
        assert 1 <= pad <= 8
        assert (16 + size + 4 + 8 + pad) % 8 == 0
        assert actual_size(size, 3) == 16 + size + 4 + 8 + pad


def test_empty_data_needle():
    n = Needle(id=5, cookie=6, data=b"", name=b"ignored-when-empty")
    buf = n.to_bytes()
    m = Needle.from_bytes(buf)
    assert m.size == 0 and m.data == b""


# --- superblock -------------------------------------------------------------


def test_superblock_roundtrip():
    sb = SuperBlock(
        version=3,
        replica_placement=t.ReplicaPlacement.parse("001"),
        ttl=t.TTL.parse("1w"),
        compaction_revision=7,
    )
    b = sb.to_bytes()
    assert len(b) == 8
    sb2 = SuperBlock.from_bytes(b)
    assert sb2 == sb


# --- idx + needle maps ------------------------------------------------------


def test_idx_pack_parse(tmp_path):
    p = tmp_path / "x.idx"
    entries = [(1, 8, 100), (2, 136, 50), (1, 0, t.TOMBSTONE_FILE_SIZE)]
    with open(p, "wb") as f:
        for e in entries:
            f.write(idx.pack_entry(*e))
    assert list(idx.walk(str(p))) == entries
    assert idx.entry_count(str(p)) == 3


def test_compact_map_replay(tmp_path):
    p = tmp_path / "v.idx"
    with open(p, "wb") as f:
        f.write(idx.pack_entry(10, 8, 100))
        f.write(idx.pack_entry(11, 112, 200))
        f.write(idx.pack_entry(10, 0, t.TOMBSTONE_FILE_SIZE))
        f.write(idx.pack_entry(12, 320, 300))
    m = needle_map.CompactMap.load_from_idx(str(p))
    assert m.get(10) is None
    assert m.get(11) == (112, 200)
    assert len(m) == 2
    assert m.stats.deleted_count == 1
    assert m.stats.deleted_bytes == 100
    assert m.stats.maximum_key == 12


def test_memdb_sorted(tmp_path):
    p = tmp_path / "v.idx"
    with open(p, "wb") as f:
        for nid in (5, 3, 9, 1):
            f.write(idx.pack_entry(nid, nid * 8, 10))
    db = needle_map.MemDb.load_from_idx(str(p))
    assert list(db.ids) == [1, 3, 5, 9]
    assert db.get(5) == (40, 10)
    assert db.get(4) is None


# --- volume engine ----------------------------------------------------------


def _fill(v, count=20, seed=0):
    rng = np.random.default_rng(seed)
    blobs = {}
    for i in range(1, count + 1):
        data = rng.integers(0, 256, int(rng.integers(1, 2000)), dtype=np.uint8).tobytes()
        v.write(i, 0xC0FFEE + i, data, name=f"f{i}".encode())
        blobs[i] = data
    return blobs


def test_volume_write_read_delete(tmp_path):
    v = Volume(str(tmp_path), 1, collection="col")
    blobs = _fill(v)
    for nid, data in blobs.items():
        n = v.read(nid, cookie=0xC0FFEE + nid)
        assert n.data == data
    with pytest.raises(CookieMismatch):
        v.read(3, cookie=0xDEAD)
    with pytest.raises(NotFoundError):
        v.read(999)
    assert v.delete(5) > 0
    with pytest.raises(NotFoundError):
        v.read(5)
    assert v.delete(5) == 0  # second delete is a no-op
    v.close()


def test_volume_reload_from_disk(tmp_path):
    v = Volume(str(tmp_path), 2)
    blobs = _fill(v, count=10, seed=1)
    v.delete(4)
    v.close()
    v2 = Volume(str(tmp_path), 2)
    assert not v2.has(4)
    for nid, data in blobs.items():
        if nid == 4:
            continue
        assert v2.read(nid).data == data
    v2.close()


def test_volume_readonly(tmp_path):
    v = Volume(str(tmp_path), 3)
    v.read_only = True
    with pytest.raises(VolumeReadOnly):
        v.write(1, 1, b"x")
    with pytest.raises(VolumeReadOnly):
        v.delete(1)
    v.close()


def test_volume_scan_record_semantics(tmp_path):
    """scan() yields stored records in file order (superseded ones
    included — liveness is the needle map's call, as in the reference's
    ScanVolumeFile); tombstone records only appear with include_deleted."""
    v = Volume(str(tmp_path), 4)
    _fill(v, count=8, seed=2)
    v.delete(2)
    v.delete(7)
    records = [n.id for _, n in v.scan()]
    assert records == list(range(1, 9))  # originals still on disk
    live = [n.id for _, n in v.scan() if v.nm.has(n.id)]
    assert set(live) == {1, 3, 4, 5, 6, 8}
    with_tombs = [n.id for _, n in v.scan(include_deleted=True)]
    assert with_tombs == records + [2, 7]  # tombstone appends at the tail
    v.close()


def test_vacuum_reclaims_space(tmp_path):
    v = Volume(str(tmp_path), 5)
    blobs = _fill(v, count=30, seed=3)
    for nid in range(1, 16):
        v.delete(nid)
    size_before = v.content_size
    ratio = vacuum.vacuum(v)
    assert ratio > 0.3
    assert v.content_size < size_before
    assert v.super_block.compaction_revision == 1
    for nid in range(16, 31):
        assert v.read(nid).data == blobs[nid]
    for nid in range(1, 16):
        assert not v.has(nid)
    # volume still writable after vacuum
    v.write(100, 1, b"post-vacuum write")
    assert v.read(100).data == b"post-vacuum write"
    v.close()
    # and reloads cleanly
    v2 = Volume(str(tmp_path), 5)
    assert v2.read(100).data == b"post-vacuum write"
    assert needle_map.verify_index_integrity(v2.dat_path, v2.idx_path, 3) == 16
    v2.close()


def test_vacuum_with_racing_write(tmp_path):
    """makeupDiff: a write that lands between compact and commit survives."""
    v = Volume(str(tmp_path), 6)
    _fill(v, count=5, seed=4)
    v.delete(1)
    cpd, cpx, snap, shadow = vacuum.compact(v)
    v.write(50, 0xAA, b"racing write")  # lands after snapshot
    v.delete(2)  # racing delete
    vacuum.commit(v, cpd, cpx, snap, shadow)
    assert v.read(50).data == b"racing write"
    assert not v.has(2)
    assert not v.has(1)
    assert v.read(3).data  # pre-existing survives
    v.close()


def test_vacuum_after_overwrite_keeps_latest(tmp_path):
    """A needle rewritten under the same id must survive vacuum exactly
    once, with the latest contents."""
    v = Volume(str(tmp_path), 8)
    v.write(1, 0xA, b"version one")
    v.write(2, 0xB, b"other")
    v.write(1, 0xA, b"version two, the keeper")
    vacuum.vacuum(v)
    assert v.read(1).data == b"version two, the keeper"
    assert v.read(2).data == b"other"
    # exactly 2 live records on disk after vacuum
    assert len([1 for _ in v.scan()]) == 2
    v.close()


def test_tail_recovery_after_crash(tmp_path):
    """Crash between .dat append and .idx append: the record is re-indexed
    at next load; a torn partial record is ignored and healed."""
    v = Volume(str(tmp_path), 9)
    v.write(1, 0xA, b"indexed record")
    # simulate: record durably in .dat, idx entry lost
    n = Needle(id=2, cookie=0xB, data=b"unindexed but complete")
    record = n.to_bytes(v.version)
    with open(v.dat_path, "ab") as f:
        f.write(record)
    # plus a torn partial record at EOF
    torn = Needle(id=3, cookie=0xC, data=b"never fully written").to_bytes(v.version)
    with open(v.dat_path, "ab") as f:
        f.write(torn[: len(torn) // 2])
    v.close()

    v2 = Volume(str(tmp_path), 9)
    assert v2.read(2).data == b"unindexed but complete"  # recovered
    assert not v2.has(3)  # torn record dropped
    v2.write(4, 0xD, b"post-recovery append")
    assert v2.read(4).data == b"post-recovery append"
    assert v2.read(1).data == b"indexed record"
    # the torn tail was truncated, not left as garbage mid-file: scan()
    # walks every record cleanly (regression: stale header desyncing vacuum)
    assert sorted(n.id for _, n in v2.scan()) == [1, 2, 4]
    from seaweedfs_tpu.storage.vacuum import vacuum

    vacuum(v2)
    assert sorted(n.id for _, n in v2.scan()) == [1, 2, 4]
    assert v2.read(4).data == b"post-recovery append"
    v2.close()
    # idempotent: loading again recovers nothing new
    v3 = Volume(str(tmp_path), 9)
    assert sorted(nid for nid, _, _ in v3.nm.items()) == [1, 2, 4]
    v3.close()


def test_compact_leaves_live_superblock_untouched(tmp_path):
    v = Volume(str(tmp_path), 10)
    v.write(1, 0xA, b"x")
    cpd, cpx, snap, shadow = vacuum.compact(v)
    assert v.super_block.compaction_revision == 0  # bump only lands at commit
    vacuum.commit(v, cpd, cpx, snap, shadow)
    assert v.super_block.compaction_revision == 1
    v.close()


def test_scan_stops_at_torn_tail(tmp_path):
    v = Volume(str(tmp_path), 11)
    v.write(1, 0xA, b"whole record")
    v.sync()
    with open(v.dat_path, "ab") as f:
        f.write(b"\xff" * 21)  # garbage partial "record"
    assert [n.id for _, n in v.scan()] == [1]  # no crash
    vacuum.vacuum(v)  # vacuum also survives
    assert v.read(1).data == b"whole record"
    v.close()


def test_index_integrity_checker(tmp_path):
    v = Volume(str(tmp_path), 7)
    _fill(v, count=5, seed=5)
    v.close()
    # corrupt the idx: point needle 3 at the wrong offset
    entries = list(idx.walk(v.idx_path))
    with open(v.idx_path, "wb") as f:
        for nid, off, size in entries:
            if nid == 3:
                off = 8
            f.write(idx.pack_entry(nid, off, size))
    with pytest.raises(ValueError, match="mismatch"):
        needle_map.verify_index_integrity(v.dat_path, v.idx_path, 3)


def test_compact_map_live_count_edge_cases():
    """len() stays O(1)-correct across size-0 entries, rewrites and deletes
    (regression: dead-on-arrival entries counted as live)."""
    from seaweedfs_tpu.storage.needle_map import CompactMap

    m = CompactMap()
    m.set(1, 100, 0)  # empty write: dead on arrival
    assert len(m) == 0 and not m.has(1)
    m.set(1, 200, 50)  # rewrite with real data
    assert len(m) == 1
    m.set(1, 300, 60)  # supersede
    assert len(m) == 1
    m.set(2, 400, 10)
    assert len(m) == 2
    m.delete(1)
    assert len(m) == 1
    m.delete(1)  # double delete: no change
    assert len(m) == 1
    m.delete(99)  # absent: no change
    assert len(m) == 1


def test_read_deleted_until_vacuum(tmp_path):
    """?readDeleted=true semantics (reference ReadOption.ReadDeleted): a
    deleted needle stays readable from its original record until vacuum
    reclaims it."""
    from seaweedfs_tpu.storage.disk_location import DiskLocation
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.store import Store
    from seaweedfs_tpu.storage.volume import NotFoundError

    store = Store([DiskLocation(str(tmp_path))])
    store.add_volume(1)
    store.write_needle(1, Needle(id=7, cookie=3, data=b"forensics" * 10))
    store.write_needle(1, Needle(id=8, cookie=3, data=b"keep"))
    assert store.delete_needle(1, 7, 3) > 0

    with pytest.raises((NotFoundError, KeyError)):
        store.read_needle(1, 7, 3)
    n = store.read_needle(1, 7, 3, read_deleted=True)
    assert n.data == b"forensics" * 10
    # wrong cookie still refused even on forensic reads
    from seaweedfs_tpu.storage.volume import CookieMismatch

    with pytest.raises(CookieMismatch):
        store.read_needle(1, 7, 999, read_deleted=True)

    # throttle hint sees the original size through the tombstone
    v = store.find_volume(1)
    assert v.deleted_needle_size(7) >= len(b"forensics" * 10)

    store.vacuum_volume(1)
    with pytest.raises((NotFoundError, KeyError)):
        store.read_needle(1, 7, 3, read_deleted=True)
    assert store.read_needle(1, 8, 3).data == b"keep"


def test_read_deleted_on_persistent_map(tmp_path):
    """The persistent (SQLite) needle map keeps tombstone offsets too, so
    forensic reads work on -index sqlite volumes as well."""
    from seaweedfs_tpu.storage.disk_location import DiskLocation
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.store import Store

    store = Store(
        [DiskLocation(str(tmp_path), needle_map_kind="persistent")]
    )
    store.add_volume(2)
    store.write_needle(2, Needle(id=5, cookie=1, data=b"sql-forensics"))
    assert store.delete_needle(2, 5, 1) > 0
    n = store.read_needle(2, 5, 1, read_deleted=True)
    assert n.data == b"sql-forensics"
