"""Store + DiskLocation: multi-dir registry, discovery, EC lifecycle.

Mirrors the reference's store-backed unit tests, which run against real
files in temp dirs (SURVEY.md §4: storage/volume_read_test.go etc.).
"""
import os

import pytest

from seaweedfs_tpu.storage import needle as needle_mod
from seaweedfs_tpu.storage.disk_location import DiskLocation, parse_base_name
from seaweedfs_tpu.storage.ec import TOTAL_SHARDS, to_ext
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import NotFoundError


def make_store(tmp_path, ndirs=2, max_count=4):
    locs = [
        DiskLocation(str(tmp_path / f"d{i}"), max_volume_count=max_count)
        for i in range(ndirs)
    ]
    return Store(locs, ip="127.0.0.1", port=8080)


def put(store, vid, nid, data, cookie=0x1234):
    n = Needle(id=nid, cookie=cookie, data=data)
    store.write_needle(vid, n)
    return n


def test_parse_base_name():
    assert parse_base_name("7") == ("", 7)
    assert parse_base_name("col_7") == ("col", 7)
    assert parse_base_name("a_b_7") == ("a_b", 7)
    assert parse_base_name("junk") is None


def test_add_write_read_delete(tmp_path):
    store = make_store(tmp_path)
    store.add_volume(1, collection="pics")
    put(store, 1, 101, b"hello world")
    n = store.read_needle(1, 101, cookie=0x1234)
    assert n.data == b"hello world"
    assert store.delete_needle(1, 101) > 0
    with pytest.raises(KeyError):
        store.read_needle(1, 101)
    store.close()


def test_placement_spreads_by_free_slots(tmp_path):
    store = make_store(tmp_path, ndirs=2, max_count=2)
    for vid in range(1, 5):
        store.add_volume(vid)
    counts = sorted(len(loc.volumes) for loc in store.locations)
    assert counts == [2, 2]
    with pytest.raises(RuntimeError):
        store.add_volume(9)
    store.close()


def test_discovery_reload(tmp_path):
    store = make_store(tmp_path)
    store.add_volume(3, collection="c")
    put(store, 3, 7, b"persisted")
    store.close()

    store2 = make_store(tmp_path)
    n = store2.read_needle(3, 7, cookie=0x1234)
    assert n.data == b"persisted"
    assert store2.find_volume(3).collection == "c"
    store2.close()


def test_heartbeat_state_and_deltas(tmp_path):
    store = make_store(tmp_path)
    hs = store.collect_heartbeat()
    assert hs.has_no_volumes and hs.has_no_ec_shards
    assert hs.max_volume_counts == {"hdd": 8}

    store.add_volume(1)
    put(store, 1, 5, b"x" * 100)
    hs = store.collect_heartbeat()
    assert len(hs.volumes) == 1
    assert hs.volumes[0].file_count == 1

    new_v, del_v, new_ec, del_ec = store.drain_deltas()
    assert [m.id for m in new_v] == [1]
    assert not del_v and not new_ec and not del_ec

    store.delete_volume(1)
    _, del_v, _, _ = store.drain_deltas()
    assert [m.id for m in del_v] == [1]
    store.close()


def test_ec_generate_mount_read_degraded(tmp_path):
    store = make_store(tmp_path)
    store.add_volume(2)
    blobs = {nid: os.urandom(500 + nid * 37) for nid in range(1, 20)}
    for nid, data in blobs.items():
        put(store, 2, nid, data)

    store.ec_generate(2)
    loc = store.location_of_volume(2)
    store.mount_ec_shards(2, list(range(TOTAL_SHARDS)))
    store.unmount_volume(2)

    # normal EC read through the store dispatch
    for nid, data in blobs.items():
        assert store.read_needle(2, nid, cookie=0x1234).data == data

    # kill 3 shards on disk and unmount them -> degraded reads still work
    ev = store.find_ec_volume(2)
    for sid in (0, 5, 12):
        s = ev.delete_shard(sid)
        s.destroy()
    for nid, data in blobs.items():
        assert store.read_ec_needle(2, nid).data == data

    # EC heartbeat reflects the remaining shard bits
    hs = store.collect_heartbeat()
    assert len(hs.ec_shards) == 1
    bits = hs.ec_shards[0].ec_index_bits
    assert bin(bits).count("1") == TOTAL_SHARDS - 3
    store.close()


def test_ec_rebuild_after_loss(tmp_path):
    store = make_store(tmp_path)
    store.add_volume(4)
    blobs = {nid: os.urandom(256) for nid in range(1, 8)}
    for nid, data in blobs.items():
        put(store, 4, nid, data)
    store.ec_generate(4)
    base = store.find_volume(4).base_name(
        store.location_of_volume(4).directory, 4
    )
    store.unmount_volume(4)

    for sid in (1, 13):
        os.remove(base + to_ext(sid))
    rebuilt = store.ec_rebuild(4)
    assert sorted(rebuilt) == [1, 13]

    store.mount_ec_shards(4, list(range(TOTAL_SHARDS)))
    for nid, data in blobs.items():
        assert store.read_ec_needle(4, nid).data == data
    store.close()


def test_ec_discovery_reload(tmp_path):
    store = make_store(tmp_path, ndirs=1)
    store.add_volume(6)
    put(store, 6, 42, b"ec persisted")
    store.ec_generate(6)
    store.mount_ec_shards(6, list(range(TOTAL_SHARDS)))
    store.unmount_volume(6)
    store.close()

    store2 = make_store(tmp_path, ndirs=1)
    ev = store2.find_ec_volume(6)
    assert ev is not None and len(ev.shards) == TOTAL_SHARDS
    assert store2.read_needle(6, 42).data == b"ec persisted"
    store2.close()


def test_delete_ec_shards_cleans_sidecars(tmp_path):
    store = make_store(tmp_path, ndirs=1)
    store.add_volume(8)
    put(store, 8, 1, b"bye")
    store.ec_generate(8)
    store.mount_ec_shards(8, list(range(TOTAL_SHARDS)))
    base = store.find_ec_volume(8).base_name
    store.unmount_volume(8)

    store.delete_ec_shards(8, list(range(TOTAL_SHARDS)))
    assert store.find_ec_volume(8) is None
    for ext in [".ecx", ".ecj", ".vif"] + [to_ext(i) for i in range(TOTAL_SHARDS)]:
        assert not os.path.exists(base + ext)
    store.close()


def test_readonly_and_unknown_volume(tmp_path):
    store = make_store(tmp_path)
    store.add_volume(9)
    store.mark_volume_readonly(9)
    with pytest.raises(Exception):
        put(store, 9, 1, b"nope")
    store.mark_volume_readonly(9, read_only=False)
    put(store, 9, 1, b"ok")
    with pytest.raises(NotFoundError):
        store.read_needle(99, 1)
    store.close()
