"""Crash recovery (.note marker + torn-tail healing) and incremental
replica sync via tail.

Reference: volume_write.go:85 (.note marker), volume_checking.go
CheckAndFixVolumeDataIntegrity (load-time heal), volume_grpc_tail.go
VolumeTailSender/Receiver, operation/tail_volume.go.
"""
import asyncio
import os
import subprocess
import sys
import time

import pytest

from seaweedfs_tpu.pb import Stub, channel, volume_server_pb2
from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.storage.volume import Volume

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------- .note


def test_note_marker_lifecycle(tmp_path):
    v = Volume(str(tmp_path), 1)
    assert os.path.exists(v.note_path), "open volume is marked dirty"
    v.write(1, 0x11, b"hello")
    v.close()
    assert not os.path.exists(v.note_path), "clean close removes the marker"
    v2 = Volume(str(tmp_path), 1)
    assert v2.read(1).data == b"hello"
    v2.close()


def test_kill_mid_write_recovers(tmp_path):
    """SIGKILL a writer process mid-append; the reload must keep every
    fully-written needle, heal the torn tail, and accept new writes."""
    script = f"""
import os, sys, time
sys.path.insert(0, {REPO!r})
from seaweedfs_tpu.storage.volume import Volume
v = Volume({str(tmp_path)!r}, 7)
i = 1
while True:
    v.write(i, 0xAB, os.urandom(2048))
    if i == 50:
        print("ready", flush=True)
    i += 1
"""
    p = subprocess.Popen(
        [sys.executable, "-c", script],
        stdout=subprocess.PIPE,
        env=dict(os.environ, SWFS_NO_NATIVE_BUILD="1"),
    )
    try:
        line = p.stdout.readline()
        assert b"ready" in line
    finally:
        p.kill()
        p.wait()

    assert os.path.exists(os.path.join(str(tmp_path), "7.note")), (
        "killed process leaves the dirty marker"
    )
    v = Volume(str(tmp_path), 7)
    # every acked needle (>= 50 of them) is intact
    for i in range(1, 51):
        assert v.read(i, 0xAB).data and len(v.read(i).data) == 2048
    # the healed volume accepts new writes on a clean record boundary
    v.write(1000, 0xCD, b"after recovery")
    assert v.read(1000).data == b"after recovery"
    v.close()
    assert not os.path.exists(v.note_path)


# ---------------------------------------------------------------- tail search


def test_find_offset_since(tmp_path):
    v = Volume(str(tmp_path), 2)
    stamps = []
    for i in range(1, 11):
        v.write(i, 0, f"needle-{i}".encode())
        stamps.append(v.read(i).append_at_ns)
    assert stamps == sorted(stamps)
    # the cursor backs up one live record (so interleaved tombstones are
    # never skipped); the sender filters by timestamp
    off = v.find_offset_since(stamps[4])
    newer = [
        n.id
        for _, _, _, n in v.scan_records(off)
        if n.append_at_ns > stamps[4]
    ]
    assert newer == list(range(6, 11))
    # cursor at the newest stamp -> nothing newer survives the filter
    off = v.find_offset_since(stamps[-1])
    assert [
        n.id
        for _, _, _, n in v.scan_records(off)
        if n.append_at_ns > stamps[-1]
    ] == []
    # zero cursor -> everything
    assert len(list(v.scan_records(v.find_offset_since(0)))) == 10
    v.close()


# ---------------------------------------------------------------- e2e tail


def test_replica_catches_up_via_tail(tmp_path):
    """Write needles on server A, allocate an empty volume on server B,
    then B pulls A's appends via VolumeTailReceiver."""

    async def go():
        cluster = LocalCluster(base_dir=str(tmp_path), n_volume_servers=2)
        await cluster.start()
        try:
            vs_a, vs_b = cluster.volume_servers
            stub_a = Stub(
                channel(vs_a.grpc_url), volume_server_pb2, "VolumeServer"
            )
            stub_b = Stub(
                channel(vs_b.grpc_url), volume_server_pb2, "VolumeServer"
            )
            vid = 91
            await stub_a.AllocateVolume(
                volume_server_pb2.AllocateVolumeRequest(
                    volume_id=vid, collection="", replication="000", ttl=""
                )
            )
            payloads = {}
            for i in range(1, 21):
                data = os.urandom(1024 + i)
                payloads[i] = data
                await asyncio.to_thread(
                    vs_a.store.find_volume(vid).write, i, 0x5A, data
                )

            await stub_b.AllocateVolume(
                volume_server_pb2.AllocateVolumeRequest(
                    volume_id=vid, collection="", replication="000", ttl=""
                )
            )
            source = f"{vs_a.ip}:{vs_a.port}.{vs_a.grpc_port}"
            await stub_b.VolumeTailReceiver(
                volume_server_pb2.VolumeTailReceiverRequest(
                    volume_id=vid,
                    since_ns=0,
                    idle_timeout_seconds=1,
                    source_volume_server=source,
                )
            )
            vb = vs_b.store.find_volume(vid)
            for i, data in payloads.items():
                assert vb.read(i, 0x5A).data == data

            # incremental: more writes on A, resume from B's newest stamp
            last_ns = max(vb.read(i).append_at_ns for i in payloads)
            for i in range(21, 26):
                data = os.urandom(512)
                payloads[i] = data
                await asyncio.to_thread(
                    vs_a.store.find_volume(vid).write, i, 0x5A, data
                )
            await stub_b.VolumeTailReceiver(
                volume_server_pb2.VolumeTailReceiverRequest(
                    volume_id=vid,
                    since_ns=last_ns,
                    idle_timeout_seconds=1,
                    source_volume_server=source,
                )
            )
            for i in range(21, 26):
                assert vb.read(i, 0x5A).data == payloads[i]
            assert len(vb.nm) == 25

            # deletes propagate: tombstone records ride the tail too
            last_ns = max(vb.read(i).append_at_ns for i in range(21, 26))
            va = vs_a.store.find_volume(vid)
            await asyncio.to_thread(va.delete, 3)
            await stub_b.VolumeTailReceiver(
                volume_server_pb2.VolumeTailReceiverRequest(
                    volume_id=vid,
                    since_ns=last_ns,
                    idle_timeout_seconds=1,
                    source_volume_server=source,
                )
            )
            from seaweedfs_tpu.storage.volume import NotFoundError

            with pytest.raises(NotFoundError):
                vb.read(3)
        finally:
            await cluster.stop()

    run(go())
