"""Tail-latency forensics plane (obs/critpath.py + obs/tailstore.py):
tail-based retention under churn, cross-node assembly with clock-skew
reconciliation, client-anchored critical-path attribution, and the
end-to-end degraded read crossing filer -> volume -> remote-shard hops.

Reference: the Dapper trace model in obs/trace.py; the acceptance
arithmetic here is the same bucketing bench_tailpath_sweep gates on.
"""
import asyncio
import time

import aiohttp
import pytest

from seaweedfs_tpu import obs, stats
from seaweedfs_tpu.obs import critpath, tailstore
from seaweedfs_tpu.obs import trace as obs_trace


def run(coro):
    return asyncio.run(coro)


def _finish_one(name="GET /1,aabbcc", dur_s=0.0, trace_id=None,
                flag_store=None, flag_kind=None):
    """Finish one root trace with a faked duration (t0 rewound so the
    perf-counter delta IS the duration — finish_trace stamps end)."""
    t, tok = obs.start_trace(name, "volume", "vs1", trace_id=trace_id)
    t.t0 -= dur_s
    if flag_store is not None:
        flag_store.flag(t.trace_id, flag_kind or "qos_shed")
    obs.finish_trace(t, tok, 200)
    return t.trace_id


# ------------------------------------------------------------- retention


def test_tail_ring_retention_under_churn():
    """A pinned slow tree survives hundreds of fast requests: fast
    requests never pass the pin gate, so they can never evict it — and
    the pin's FROZEN entries outlive the main ring's churn too."""
    store = tailstore.TailStore(node="vs1", capacity=8, floor_ms=50.0)
    store.install()
    try:
        slow_id = _finish_one(dur_s=0.2)
        pins = store.snapshot(trace_id=slow_id)
        assert len(pins) == 1 and pins[0]["reason"] == "floor"
        assert pins[0]["entries"], "pin froze no span tree"

        # churn: enough fast roots to wrap the MAIN trace ring many
        # times over — none is slow enough to enter the tail ring
        for _ in range(max(obs_trace.CONFIG.trace_ring, 256) * 2):
            _finish_one(dur_s=0.0)

        assert not obs_trace.RING.snapshot(trace_id=slow_id), (
            "churn was not enough to evict the slow trace from the "
            "main ring — the retention half of this test needs that"
        )
        pins = store.snapshot(trace_id=slow_id)
        assert len(pins) == 1, "fast churn evicted the pinned slow tree"
        assert pins[0]["entries"]
        # the module-level resolver (what /debug/traces?id= falls back
        # to) and the assembler's local view both still find it
        assert tailstore.pinned(slow_id)
        assert critpath.local_entries(slow_id)
    finally:
        store.uninstall()


def test_tail_ring_bounded_newest_pins_win():
    store = tailstore.TailStore(node="vs1", capacity=4, floor_ms=10.0)
    store.install()
    try:
        ids = [_finish_one(dur_s=0.05) for _ in range(9)]
        pins = store.snapshot()
        assert len(pins) == 4, "tail ring exceeded its capacity"
        assert [p["trace_id"] for p in pins] == list(reversed(ids[-4:]))
    finally:
        store.uninstall()


def test_incident_flag_pins_a_fast_trace():
    """A QoS-shaped request pins regardless of latency — the decision
    itself is the evidence — while non-trigger kinds are ignored."""
    store = tailstore.TailStore(node="vs1", capacity=4, floor_ms=1e9)
    store.install()
    try:
        fast_id = _finish_one(dur_s=0.0, flag_store=store,
                              flag_kind="hedge")
        pins = store.snapshot(trace_id=fast_id)
        assert len(pins) == 1 and pins[0]["reason"] == "incident:hedge"

        # flag_ambient: trigger kinds fan to installed stores, others no-op
        t, tok = obs.start_trace("GET /2,dd", "volume", "vs1")
        tailstore.flag_ambient("compile_storm", t.trace_id)  # not a trigger
        obs.finish_trace(t, tok, 200)
        assert not store.snapshot(trace_id=t.trace_id)
    finally:
        store.uninstall()


def test_set_floor_ms_validation():
    store = tailstore.TailStore(node="vs1", capacity=4, floor_ms=0.0)
    with pytest.raises(ValueError):
        store.set_floor_ms(-1.0)
    store.install()
    try:
        no_pin = _finish_one(dur_s=0.05)
        assert not store.snapshot(trace_id=no_pin)  # floor 0 = off
        store.set_floor_ms(10.0)
        pinned_id = _finish_one(dur_s=0.05)
        assert store.snapshot(trace_id=pinned_id)
    finally:
        store.uninstall()


# -------------------------------------------------------------- assembly


def _parent_child_entries(child_wall_skew_ms=0.0):
    """A two-node trace: filerA's root with a chunk_fetch call span,
    and volB's child entry hanging off that span id.  The child truly
    started 15ms into the parent; its wall clock reads
    `child_wall_skew_ms` AHEAD of true time."""
    parent = {
        "trace_id": "T1", "role": "filer", "server": "filerA",
        "name": "GET /blob.bin", "parent_span_id": "",
        "root_span_id": "R", "start_unix_ms": 1_000_000,
        "duration_us": 100_000, "status": "200",
        "spans": [{
            "name": "chunk_fetch", "span_id": "S1", "parent_id": "R",
            "offset_us": 10_000, "duration_us": 80_000,
        }],
    }
    child = {
        "trace_id": "T1", "role": "volume", "server": "volB",
        "name": "GET /1,aa", "parent_span_id": "S1",
        "root_span_id": "C",
        "start_unix_ms": 1_000_015 + int(child_wall_skew_ms),
        "duration_us": 60_000, "status": "200",
        "spans": [{
            "name": "device_execute", "span_id": "D1", "parent_id": "C",
            "offset_us": 5_000, "duration_us": 50_000,
        }],
    }
    return parent, child


def test_clock_skew_reconciliation():
    """The heartbeat skew estimate places a deliberately skewed child
    where it actually ran; without the estimate, the parent-side call
    span window clamps the child so it can never appear to run outside
    the RPC that invoked it."""
    parent, child = _parent_child_entries(child_wall_skew_ms=5_000.0)

    doc = critpath.assemble([parent, child],
                            skew_ms={"volB": 5_000.0})
    vol = next(p for p in doc["participants"] if p["role"] == "volume")
    assert vol["offset_us"] == 15_000  # skew-corrected true start
    assert doc["total_us"] == 100_000

    # no estimate: the raw 5s-ahead wall clock would place the child
    # AFTER its parent ended — the clamp pins it to the latest start
    # that still fits inside the chunk_fetch window
    doc = critpath.assemble([parent, child])
    vol = next(p for p in doc["participants"] if p["role"] == "volume")
    assert vol["offset_us"] == 30_000  # 10_000 + (80_000 - 60_000)
    assert vol["offset_us"] + 60_000 <= 10_000 + 80_000

    # either way the six segments sum exactly to the root total, and
    # the child's device time outranks the covering network-call span
    assert sum(doc["segments_us"].values()) == doc["total_us"]
    assert doc["segments_us"]["device_execute"] == 50_000
    assert doc["segments_us"]["network_gap"] == 30_000  # 80k - 50k
    assert doc["segments_us"]["untraced"] == 20_000


def test_client_anchored_assembly_puts_wire_legs_in_network_gap():
    """Anchoring on the client-measured total classifies the slice of
    wall time outside the root handler span as network_gap — wire +
    handoff legs no server span can see — never as untraced."""
    parent, child = _parent_child_entries()
    doc = critpath.assemble([parent, child], skew_ms={},
                            client_total_us=120_000)
    assert doc["total_us"] == 120_000
    assert doc["server_total_us"] == 100_000
    assert sum(doc["segments_us"].values()) == 120_000
    assert doc["segments_us"]["network_gap"] == 30_000 + 20_000
    assert doc["segments_us"]["untraced"] == 20_000  # unchanged

    # a client total BELOW the server span is clock noise, not a leg:
    # the anchor never shrinks the timeline
    doc = critpath.assemble([parent, child], skew_ms={},
                            client_total_us=90_000)
    assert doc["total_us"] == 100_000


def test_assemble_dedupes_ring_and_pin_copies():
    """The same entry arriving via the live ring AND a tail pin (or two
    node urls of a co-hosted process) must not double its spans."""
    parent, child = _parent_child_entries()
    doc = critpath.assemble([parent, child, dict(parent), dict(child)])
    assert len(doc["participants"]) == 2
    assert doc["segments_us"]["device_execute"] == 50_000


# ------------------------------------------------------------ end-to-end


def test_degraded_read_assembly_across_hops(tmp_path):
    """A degraded EC read through the filer, resolved via the
    /debug/critpath front door: the assembled DAG spans the filer hop,
    the volume's dispatcher pipeline, and the remote-shard fetches; the
    client-anchored segments sum to the client-measured total; a bogus
    id gets the 404 contract on both forensics endpoints."""
    from bench import build_degraded_cluster

    async def go():
        # host reconstruct path (no device cache): a read touching a
        # DESTROYED shard must try the remote-shard lane before it
        # reconstructs — that hop is the span under test, and it is
        # deterministic here where the device-resident path may serve
        # everything from cache depending on compile warmth
        cluster, vs, blobs, _vid = await build_degraded_cluster(
            str(tmp_path), n_blobs=6, blob_size=lambda i: 4096,
            device_cache=False, drop_shards=(0, 11), with_filer=True,
        )
        try:
            fs = cluster.filer
            from seaweedfs_tpu.filer import Attr, Entry
            from seaweedfs_tpu.pb import filer_pb2

            now = int(time.time())
            for i, (fid, data) in enumerate(blobs.items()):
                await fs.filer.create_entry(
                    Entry(
                        full_path=f"/blob{i}.bin",
                        attr=Attr(
                            mtime=now, crtime=now, file_size=len(data)
                        ),
                        chunks=[
                            filer_pb2.FileChunk(
                                file_id=fid, offset=0, size=len(data)
                            )
                        ],
                    )
                )

            def names(n):
                yield from (sp["name"] for sp in n["spans"])
                for c in n["children"]:
                    yield from names(c)

            async with aiohttp.ClientSession() as sess:
                # read every blob; at least one lives on a destroyed
                # shard and must cross the remote-shard lane before it
                # reconstructs — THAT assembled trace is under test
                hop_doc = None
                for i, (fid, data) in enumerate(blobs.items()):
                    t0 = time.perf_counter()
                    async with sess.get(
                        f"http://{fs.url}/blob{i}.bin"
                    ) as r:
                        assert r.status == 200
                        assert await r.read() == data
                        hdr = r.headers.get(obs.TRACE_HEADER, "")
                    client_us = int((time.perf_counter() - t0) * 1e6)
                    trace_id, _ = obs.parse_trace_header(hdr)
                    assert trace_id

                    async with sess.get(
                        f"http://{cluster.master.url}/debug/critpath",
                        params={"id": trace_id,
                                "client_total_us": str(client_us)},
                        allow_redirects=True,
                    ) as r:
                        assert r.status == 200, await r.text()
                        doc = await r.json()

                    roles = {p["role"] for p in doc["participants"]}
                    assert {"filer", "volume"} <= roles, (
                        doc["participants"]
                    )
                    assert doc["tree"]["children"], "hops did not link"
                    # client-anchored arithmetic on every read: the six
                    # segments sum to the client-visible total, exactly
                    assert doc["total_us"] == max(
                        client_us, doc["server_total_us"]
                    )
                    assert (
                        sum(doc["segments_us"].values()) == doc["total_us"]
                    )
                    assert doc["route"] == f"GET /blob{i}.bin"
                    if hop_doc is None and (
                        "remote_shard_read" in set(names(doc["tree"]))
                    ):
                        hop_doc = doc

                assert hop_doc is not None, (
                    "no degraded read crossed the remote-shard lane"
                )
                vol = next(p for p in hop_doc["participants"]
                           if p["role"] == "volume")
                assert vol["spans"] > 0

                # not-found contract, both front doors (satellite: a
                # miss is a 404 JSON error, not an empty 200)
                for path in ("/debug/critpath", "/debug/traces"):
                    async with sess.get(
                        f"http://{vs.url}{path}",
                        params={"id": "feedfacefeedface"},
                    ) as r:
                        assert r.status == 404
                        err = await r.json()
                        assert "not found" in err["error"]
                async with sess.get(
                    f"http://{vs.url}/debug/tail",
                    params={"id": "feedfacefeedface"},
                ) as r:
                    assert r.status == 404
        finally:
            await cluster.stop()

    run(go())
