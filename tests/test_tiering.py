"""Tiered storage: .dat files moved to a storage backend while reads keep
working via ranged fetches; volume.tier.upload/download shell commands;
reload-from-.vif discovery.

Reference shapes: weed/storage/backend/backend.go,
volume_grpc_tier.go, shell/command_volume_tier_upload.go /
_download.go.
"""
import asyncio
import io
import os

import aiohttp
import pytest

from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.storage import backend as backend_mod
from seaweedfs_tpu.storage.volume import Volume


def run(coro):
    return asyncio.run(coro)


def test_local_backend_roundtrip(tmp_path):
    b = backend_mod.LocalBackendStorage("default", str(tmp_path / "store"))
    src = tmp_path / "f.dat"
    src.write_bytes(b"0123456789" * 1000)
    assert b.upload(str(src), "1.dat") == 10_000
    assert b.size("1.dat") == 10_000
    assert b.pread("1.dat", 10, 20) == b"0123456789"
    dst = tmp_path / "back.dat"
    b.download("1.dat", str(dst))
    assert dst.read_bytes() == src.read_bytes()
    b.delete_key("1.dat")
    with pytest.raises(FileNotFoundError):
        b.size("1.dat")


def test_backend_registry_configure(tmp_path):
    backend_mod.configure(
        {"local.cold": {"type": "local", "dir": str(tmp_path / "cold")}}
    )
    assert backend_mod.get_backend("local", "cold").name == "local.cold"
    with pytest.raises(KeyError):
        backend_mod.get_backend("local", "nope")


def test_tier_upload_download_e2e(tmp_path):
    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, volume_size_limit_mb=8
        )
        await cluster.start()
        try:
            from seaweedfs_tpu.operation import assign, upload_data

            master = cluster.master.advertise_url
            a0 = await assign(master)
            vid = int(a0.fid.split(",")[0])
            blobs = {}
            for i in range(10):
                ai = await assign(master)
                if int(ai.fid.split(",")[0]) != vid:
                    continue
                data = os.urandom(5000 + i * 777)
                await upload_data(f"http://{ai.url}/{ai.fid}", data)
                blobs[ai.fid] = data
            assert blobs

            env = CommandEnv([master], out=io.StringIO())
            await run_command(env, "lock")
            await run_command(
                env, f"volume.tier.upload -volumeId {vid} -dest local.default"
            )
            assert "uploaded" in env.out.getvalue()

            vs = cluster.volume_servers[0]
            v = vs.store.find_volume(vid)
            assert v.remote_dat is not None, "volume should serve from the tier"
            assert not os.path.exists(v.dat_path), ".dat must be gone locally"
            tier_dir = os.path.join(str(tmp_path), "tier")
            assert os.listdir(tier_dir), "backend holds the .dat"

            async with aiohttp.ClientSession() as s:
                for fid, data in blobs.items():
                    async with s.get(f"http://{vs.url}/{fid}") as r:
                        assert r.status == 200, fid
                        assert await r.read() == data, fid

            # writes must be refused on a tiered volume
            import aiohttp as _a

            async with _a.ClientSession() as s:
                fid0 = next(iter(blobs))
                async with s.post(
                    f"http://{vs.url}/{fid0}", data=b"nope"
                ) as r:
                    assert r.status >= 400

            # bring it back
            await run_command(env, f"volume.tier.download -volumeId {vid}")
            assert "downloaded" in env.out.getvalue()
            v2 = vs.store.find_volume(vid)
            assert v2.remote_dat is None
            assert os.path.exists(v2.dat_path)
            async with aiohttp.ClientSession() as s:
                for fid, data in blobs.items():
                    async with s.get(f"http://{vs.url}/{fid}") as r:
                        assert r.status == 200 and await r.read() == data
        finally:
            await cluster.stop()

    run(go())


def _store_with_volume(tmp_path, vid=7, n_needles=10):
    vdir = str(tmp_path / "v")
    os.makedirs(vdir, exist_ok=True)
    from seaweedfs_tpu.storage.disk_location import DiskLocation
    from seaweedfs_tpu.storage.store import Store

    v = Volume(vdir, vid)
    payloads = {i: os.urandom(500 + i) for i in range(1, n_needles + 1)}
    for nid, data in payloads.items():
        v.write(nid, 0xABC, data)
    v.read_only = True
    loc = DiskLocation(vdir, max_volume_count=4)
    loc.volumes[vid] = v
    return Store([loc]), payloads


def test_keep_local_stays_tiered_and_readonly(tmp_path):
    """keep_local_dat_file: the volume serves the local copy, refuses
    writes, can still be tier-downloaded, and reloads readonly."""
    backend_mod.configure(
        {"local.default": {"type": "local", "dir": str(tmp_path / "tier")}}
    )
    store, payloads = _store_with_volume(tmp_path)
    store.tier_move_to_remote(7, "local.default", keep_local=True)
    v = store.find_volume(7)
    assert os.path.exists(v.dat_path), "local copy kept"
    assert v.is_tiered and v.read_only
    from seaweedfs_tpu.storage.volume import VolumeReadOnly

    with pytest.raises(VolumeReadOnly):
        v.write(999, 0xABC, b"divergence")
    with pytest.raises(ValueError):
        store.mark_volume_readonly(7, read_only=False)
    with pytest.raises(ValueError):
        store.vacuum_volume(7)
    # download resolves the tiered state even though .dat never left
    store.tier_move_from_remote(7)
    v2 = store.find_volume(7)
    assert not v2.is_tiered
    for nid, data in payloads.items():
        assert v2.read(nid, 0xABC).data == data


def test_replicas_use_distinct_backend_keys(tmp_path):
    """Two stores (replicas) tiering the same volume id must not share a
    backend object — one replica's download+delete can't destroy the
    other's data."""
    backend_mod.configure(
        {"local.default": {"type": "local", "dir": str(tmp_path / "tier")}}
    )
    s1, p1 = _store_with_volume(tmp_path / "r1")
    s2, p2 = _store_with_volume(tmp_path / "r2")
    s1.port, s2.port = 8081, 8082
    s1.tier_move_to_remote(7, "local.default")
    s2.tier_move_to_remote(7, "local.default")
    assert len(os.listdir(str(tmp_path / "tier"))) == 2
    s1.tier_move_from_remote(7)  # deletes ONLY s1's object
    v2 = s2.find_volume(7)
    for nid, data in p2.items():
        assert v2.read(nid, 0xABC).data == data


def test_tiered_volume_reloads_from_vif(tmp_path):
    """A tiered volume (only .idx + .vif on disk) is rediscovered after a
    volume-object reload and still serves every needle."""
    backend_mod.configure(
        {"local.default": {"type": "local", "dir": str(tmp_path / "tier")}}
    )
    vdir = str(tmp_path / "v")
    os.makedirs(vdir)
    v = Volume(vdir, 7)
    payloads = {i: os.urandom(1000 + i) for i in range(1, 20)}
    for nid, data in payloads.items():
        v.write(nid, 0xABC, data)
    v.read_only = True
    from seaweedfs_tpu.storage.store import Store
    from seaweedfs_tpu.storage.disk_location import DiskLocation

    loc = DiskLocation(vdir, max_volume_count=4)
    loc.volumes[7] = v
    store = Store([loc])
    store.tier_move_to_remote(7, "local.default")
    assert not os.path.exists(v.dat_path)

    # fresh discovery, as after a process restart
    loc2 = DiskLocation(vdir, max_volume_count=4)
    loc2.load_existing_volumes()
    assert 7 in loc2.volumes
    v2 = loc2.volumes[7]
    assert v2.remote_dat is not None and v2.read_only
    for nid, data in payloads.items():
        assert v2.read(nid, 0xABC).data == data
    # scan (vacuum/ec path) works over the remote dat too
    assert len(list(v2.scan())) == len(payloads)
