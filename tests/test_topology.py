"""Master control plane tested with fake heartbeats — multi-node without
processes, the reference's approach (topology_test.go:1-210,
volume_growth_test.go:1-348)."""
import random

import pytest

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.store import EcShardMessage, HeartbeatState, VolumeMessage
from seaweedfs_tpu.topology import (
    MemorySequencer,
    NoFreeSpace,
    Topology,
    VolumeGrowOption,
    VolumeGrowth,
    scan_and_vacuum,
    target_count_per_request,
)


def vol(vid, size=1000, collection="", rp="000", read_only=False, disk="hdd"):
    return VolumeMessage(
        id=vid,
        size=size,
        collection=collection,
        file_count=1,
        delete_count=0,
        deleted_byte_count=0,
        read_only=read_only,
        replica_placement=int(rp),
        version=3,
        ttl=0,
        disk_type=disk,
    )


def heartbeat(volumes=(), ec=(), max_counts=None):
    return HeartbeatState(
        volumes=list(volumes),
        ec_shards=list(ec),
        max_volume_counts=max_counts or {"hdd": 10},
    )


def build_topo(layout):
    """layout: {dc: {rack: [(ip, port, max_count), ...]}} -> Topology with
    registered empty nodes."""
    topo = Topology()
    for dc, racks in layout.items():
        for rack, nodes in racks.items():
            for ip, port, maxc in nodes:
                n = topo.get_or_create_node(dc, rack, ip, port)
                topo.sync_node(n, heartbeat(max_counts={"hdd": maxc}))
    return topo


class TestHeartbeatIntake:
    def test_full_sync_registers_volumes(self):
        topo = Topology()
        n = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        new, deleted, _, _ = topo.sync_node(n, heartbeat([vol(1), vol(2)]))
        assert sorted(new) == [1, 2] and not deleted
        assert [x.url for x in topo.lookup_volume("", 1)] == ["10.0.0.1:8080"]
        assert topo.max_volume_id == 2

    def test_full_sync_detects_removed_volumes(self):
        topo = Topology()
        n = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        topo.sync_node(n, heartbeat([vol(1), vol(2)]))
        new, deleted, _, _ = topo.sync_node(n, heartbeat([vol(2)]))
        assert deleted == [1] and not new
        assert topo.lookup_volume("", 1) == []

    def test_incremental_sync(self):
        topo = Topology()
        n = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        topo.sync_node(n, heartbeat())
        topo.incremental_sync_node(n, [vol(5)], [])
        assert topo.lookup_volume("", 5)
        topo.incremental_sync_node(n, [], [vol(5)])
        assert topo.lookup_volume("", 5) == []

    def test_node_death_unregisters_everything(self):
        topo = Topology()
        n1 = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        n2 = topo.get_or_create_node("dc1", "r1", "10.0.0.2", 8080)
        topo.sync_node(n1, heartbeat([vol(1)]))
        topo.sync_node(n2, heartbeat([vol(1)]))
        topo.unregister_node(n1)
        locs = topo.lookup_volume("", 1)
        assert [x.url for x in locs] == ["10.0.0.2:8080"]
        assert topo.find_node("10.0.0.1:8080") is None

    def test_ec_shard_registration(self):
        topo = Topology()
        n1 = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        n2 = topo.get_or_create_node("dc1", "r2", "10.0.0.2", 8080)
        bits1 = sum(1 << i for i in range(7))       # shards 0-6
        bits2 = sum(1 << i for i in range(7, 14))   # shards 7-13
        topo.sync_node(n1, heartbeat(ec=[EcShardMessage(9, "", bits1, "hdd")]))
        topo.sync_node(n2, heartbeat(ec=[EcShardMessage(9, "", bits2, "hdd")]))
        locs = topo.lookup_ec_shards(9)
        assert [n.url for n in locs.locations[0]] == ["10.0.0.1:8080"]
        assert [n.url for n in locs.locations[13]] == ["10.0.0.2:8080"]
        # lookup_volume falls through to EC
        assert len(topo.lookup_volume("", 9)) == 2
        # delta-remove n1's shards
        topo.incremental_sync_node(n1, [], [], [], [EcShardMessage(9, "", bits1, "hdd")])
        assert locs.locations[0] == []


class TestPickForWrite:
    def test_round_robin_over_writables(self):
        topo = Topology()
        n = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        topo.sync_node(n, heartbeat([vol(1), vol(2), vol(3)]))
        opt = VolumeGrowOption()
        seen = set()
        for _ in range(30):
            fid, _, nodes = topo.pick_for_write(1, opt)
            vid, nid, cookie = t.parse_fid(fid)
            seen.add(vid)
            assert nodes[0].url == "10.0.0.1:8080"
        assert seen == {1, 2, 3}

    def test_readonly_and_oversized_excluded(self):
        topo = Topology(volume_size_limit=10_000)
        n = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        topo.sync_node(
            n, heartbeat([vol(1), vol(2, read_only=True), vol(3, size=20_000)])
        )
        for _ in range(10):
            fid, _, _ = topo.pick_for_write(1, VolumeGrowOption())
            assert fid.startswith("1,")

    def test_under_replicated_not_writable(self):
        topo = Topology()
        n = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        # rp=001 needs 2 copies; only one registered
        topo.sync_node(n, heartbeat([vol(1, rp="001")]))
        opt = VolumeGrowOption(replica_placement=t.ReplicaPlacement.parse("001"))
        with pytest.raises(LookupError):
            topo.pick_for_write(1, opt)

    def test_fid_ids_are_sequential(self):
        topo = Topology(sequencer=MemorySequencer(start=100))
        n = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        topo.sync_node(n, heartbeat([vol(1)]))
        fid1, _, _ = topo.pick_for_write(1, VolumeGrowOption())
        fid2, _, _ = topo.pick_for_write(3, VolumeGrowOption())
        assert t.parse_fid(fid1)[1] == 100
        assert t.parse_fid(fid2)[1] == 101
        fid3, _, _ = topo.pick_for_write(1, VolumeGrowOption())
        assert t.parse_fid(fid3)[1] == 104


class TestVolumeGrowth:
    def fabric(self):
        return build_topo(
            {
                "dc1": {"r1": [("s1", 1, 4), ("s2", 1, 4)], "r2": [("s3", 1, 4)]},
                "dc2": {"r1": [("s4", 1, 4)]},
                "dc3": {"r1": [("s5", 1, 4)]},
            }
        )

    def grow(self, topo, rp):
        g = VolumeGrowth(rng=random.Random(42))
        opt = VolumeGrowOption(replica_placement=t.ReplicaPlacement.parse(rp))
        return g.find_empty_slots(topo.data_centers, opt)

    def test_000_single_copy(self):
        servers = self.grow(self.fabric(), "000")
        assert len(servers) == 1

    def test_001_same_rack_pair(self):
        servers = self.grow(self.fabric(), "001")
        assert len(servers) == 2
        racks = {s.rack.name for s in servers}
        dcs = {s.rack.data_center.name for s in servers}
        assert len(racks) == 1 and len(dcs) == 1
        assert {s.url for s in servers} == {"s1:1", "s2:1"}

    def test_010_cross_rack(self):
        servers = self.grow(self.fabric(), "010")
        assert len(servers) == 2
        assert servers[0].rack.data_center.name == servers[1].rack.data_center.name
        assert servers[0].rack.name != servers[1].rack.name

    def test_200_three_data_centers(self):
        servers = self.grow(self.fabric(), "200")
        assert len(servers) == 3
        assert len({s.rack.data_center.name for s in servers}) == 3

    def test_011_mixed(self):
        servers = self.grow(self.fabric(), "011")
        assert len(servers) == 3
        by_rack = {}
        for s in servers:
            by_rack.setdefault((s.rack.data_center.name, s.rack.name), []).append(s)
        # one rack has 2 nodes, another rack (same dc) has 1
        sizes = sorted(len(v) for v in by_rack.values())
        assert sizes == [1, 2]

    def test_no_capacity_raises(self):
        topo = build_topo({"dc1": {"r1": [("s1", 1, 0)]}})
        with pytest.raises(NoFreeSpace):
            self.grow(topo, "000")

    def test_insufficient_dcs_raises(self):
        topo = build_topo({"dc1": {"r1": [("s1", 1, 4)]}})
        with pytest.raises(NoFreeSpace):
            self.grow(topo, "100")

    def test_grow_volumes_allocates_and_numbers(self):
        topo = self.fabric()
        allocated = []
        opt = VolumeGrowOption(replica_placement=t.ReplicaPlacement.parse("001"))
        vids = topo.grow_volumes(opt, 2, lambda n, vid, o: allocated.append((n.url, vid)))
        assert len(vids) == 2 and vids[0] != vids[1]
        assert len(allocated) == 4  # 2 volumes × 2 replicas

    def test_target_count(self):
        assert target_count_per_request(t.ReplicaPlacement.parse("000")) == 7
        assert target_count_per_request(t.ReplicaPlacement.parse("001")) == 6
        assert target_count_per_request(t.ReplicaPlacement.parse("011")) == 3
        assert target_count_per_request(t.ReplicaPlacement.parse("111")) == 1


class FakeVacuumRpc:
    def __init__(self, ratios):
        self.ratios = ratios
        self.compacted, self.committed, self.cleaned = [], [], []
        self.fail_compact_on = set()

    def check(self, node, vid):
        return self.ratios.get(vid, 0.0)

    def compact(self, node, vid):
        if node.url in self.fail_compact_on:
            return False
        self.compacted.append((node.url, vid))
        return True

    def commit(self, node, vid):
        self.committed.append((node.url, vid))
        return True

    def cleanup(self, node, vid):
        self.cleaned.append((node.url, vid))
        return True


class TestVacuumOrchestration:
    def make(self):
        topo = Topology()
        n1 = topo.get_or_create_node("dc1", "r1", "10.0.0.1", 8080)
        n2 = topo.get_or_create_node("dc1", "r1", "10.0.0.2", 8080)
        topo.sync_node(n1, heartbeat([vol(1, rp="001"), vol(2, rp="001")]))
        topo.sync_node(n2, heartbeat([vol(1, rp="001"), vol(2, rp="001")]))
        return topo

    def test_only_garbage_above_threshold(self):
        topo = self.make()
        rpc = FakeVacuumRpc({1: 0.6, 2: 0.1})
        results = scan_and_vacuum(topo, rpc, garbage_threshold=0.3)
        assert [r.vid for r in results] == [1]
        assert results[0].committed
        assert len(rpc.committed) == 2  # both replicas

    def test_failed_compact_cleans_up(self):
        topo = self.make()
        rpc = FakeVacuumRpc({1: 0.9})
        rpc.fail_compact_on = {"10.0.0.2:8080"}
        results = scan_and_vacuum(topo, rpc, garbage_threshold=0.3)
        assert not results[0].committed
        assert len(rpc.cleaned) == 2
        assert not rpc.committed

    def test_volume_stays_writable_after(self):
        topo = self.make()
        rpc = FakeVacuumRpc({1: 0.9, 2: 0.9})
        scan_and_vacuum(topo, rpc)
        _, vl = topo.layouts()[0]
        assert sorted(vl.writables) == [1, 2]
