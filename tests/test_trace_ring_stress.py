"""Threaded stress for the trace + event rings (ISSUE r17 satellite,
next to the lockwatch suite): the incident fan-out snapshots both rings
while hot paths append and configure() resizes them — every deque touch
must be lock-guarded so a snapshot can never observe a torn deque
mid-resize.  Run under lockwatch so an acquisition-order cycle between
the ring lock and the per-trace span locks would fail the test, not
deadlock it."""
from __future__ import annotations

import threading
import time

import lockwatch
from seaweedfs_tpu.obs import incident as obs_incident
from seaweedfs_tpu.obs.trace import Trace, TraceRing

N_WRITERS = 4
N_SNAPSHOTTERS = 2
DURATION_S = 1.5


def _make_trace(i: int) -> Trace:
    t = Trace(f"tid{i % 37:04x}", "volume", f"GET /{i}")
    for s, stage in enumerate(("queue_wait", "shard_read", "d2h_copy")):
        t.add_span(stage, t.t0, 0.001 * s)
    t.end = t.t0 + 0.005
    return t


def test_trace_ring_snapshot_races_add_and_resize():
    errors: list[BaseException] = []
    snapshots = [0]
    stop = threading.Event()

    with lockwatch.watch():
        ring = TraceRing(capacity=64)

        def writer(wid: int):
            i = wid
            try:
                while not stop.is_set():
                    tr = _make_trace(i)
                    ring.add(tr)
                    # spans keep landing AFTER the trace entered the
                    # ring (a finished co-hosted role's late span is
                    # exactly this shape) — to_dict must copy cleanly
                    tr.add_span("host_reconstruct", tr.t0, 0.002)
                    i += N_WRITERS
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def resizer():
            try:
                cap = 16
                while not stop.is_set():
                    ring.resize(cap)
                    cap = 16 if cap == 128 else cap * 2
                    time.sleep(0.0005)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def snapshotter():
            try:
                while not stop.is_set():
                    docs = ring.snapshot(limit=32)
                    snapshots[0] += 1
                    for d in docs:
                        # every snapshotted dict is fully formed: the
                        # span list is a consistent copy, never torn
                        assert isinstance(d["trace_id"], str)
                        assert isinstance(d["spans"], list)
                        for sp in d["spans"]:
                            assert "name" in sp and "duration_us" in sp
                    # filters race the resize too
                    ring.snapshot(trace_id="tid0001")
                    ring.snapshot(since_unix=time.time() - 5)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = (
            [threading.Thread(target=writer, args=(w,))
             for w in range(N_WRITERS)]
            + [threading.Thread(target=resizer)]
            + [threading.Thread(target=snapshotter)
               for _ in range(N_SNAPSHOTTERS)]
        )
        for t in threads:
            t.start()
        time.sleep(DURATION_S)
        stop.set()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), "stress thread wedged"

    assert not errors, errors
    assert snapshots[0] > 0
    # the final capacity bound held through every resize
    assert len(ring.snapshot()) <= 128


def test_event_ring_snapshot_races_record_and_resize():
    errors: list[BaseException] = []
    stop = threading.Event()

    with lockwatch.watch():
        ring = obs_incident.EventRing(capacity=64)

        def writer(wid: int):
            try:
                i = 0
                while not stop.is_set():
                    ring.add(
                        {
                            "unix_ms": int(time.time() * 1e3),
                            "kind": f"kind{i % 3}",
                            "trace_id": "",
                            "details": {"w": wid, "i": i},
                        }
                    )
                    i += 1
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def churner():
            try:
                cap = 8
                while not stop.is_set():
                    ring.resize(cap)
                    cap = 8 if cap == 256 else cap * 2
                    ring.snapshot(
                        since_unix=time.time() - 1, limit=16,
                        kind="kind1",
                    )
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(w,)) for w in range(3)
        ] + [threading.Thread(target=churner) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), "stress thread wedged"

    assert not errors, errors
    snap = ring.snapshot()
    # newest-first ordering survived the churn (timestamps are stamped
    # BEFORE the locked append, so concurrent writers may interleave by
    # a few ms — bounded skew, never a torn/arbitrary order)
    assert all(
        snap[i]["unix_ms"] >= snap[i + 1]["unix_ms"] - 100
        for i in range(len(snap) - 1)
    )
