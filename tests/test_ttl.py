"""TTL enforcement: expired needles 404 at read time; fully-lapsed TTL
volumes are swept away (reference: volume ttl handling in
volume_server_handlers_read.go + ttl volume expiry).
"""
import asyncio
import os
import time

import aiohttp
import pytest

from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.types import TTL


def run(coro):
    return asyncio.run(coro)


def test_ttl_read_expiry_and_sweep(tmp_path):
    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, pulse_seconds=1
        )
        await cluster.start()
        vs = cluster.volume_servers[0]
        try:
            from seaweedfs_tpu.operation import assign, upload_data

            master = cluster.master.advertise_url
            a = await assign(master, ttl="1m")
            vid = int(a.fid.split(",")[0])
            await upload_data(f"http://{a.url}/{a.fid}", b"short-lived")
            v = vs.store.find_volume(vid)
            assert v.super_block.ttl.minutes == 1

            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://{a.url}/{a.fid}") as r:
                    assert r.status == 200, "fresh needle readable"

            # age the needle: rewrite with a last_modified in the past
            nid = int(a.fid.split(",")[1][:-8] or "0", 16)
            cookie = int(a.fid.split(",")[1][-8:], 16)
            v.read_only = False
            old = Needle(
                id=nid, cookie=cookie, data=b"short-lived",
                last_modified=int(time.time()) - 120,
            )
            v.append_needle(old)
            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://{a.url}/{a.fid}") as r:
                    assert r.status == 404, "expired needle must 404"

            # volume sweep: backdate the .dat mtime past the ttl
            stale = time.time() - 600
            os.utime(v.dat_path, (stale, stale))
            deleted = vs.sweep_expired_ttl_volumes()
            assert vid in deleted
            assert vs.store.find_volume(vid) is None
            assert not os.path.exists(v.dat_path)
            # non-ttl volumes survive sweeps
            a2 = await assign(master)
            vid2 = int(a2.fid.split(",")[0])
            v2 = vs.store.find_volume(vid2)
            os.utime(v2.dat_path, (stale, stale))
            assert vs.sweep_expired_ttl_volumes() == []
            assert vs.store.find_volume(vid2) is not None
        finally:
            await cluster.stop()

    run(go())
