"""Compression, cipher, chunk cache, and image resize units, plus an e2e
encrypted+compressed filer round trip with range reads.

Reference shapes: weed/util/compression.go, util/cipher.go (AES-GCM
nonce||ct layout), util/chunk_cache/, images/resizing.go.
"""
import asyncio
import io
import os

import aiohttp
import pytest

from seaweedfs_tpu.filer.chunk_cache import ChunkCache
from seaweedfs_tpu.images import resized
from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.utils.cipher import decrypt, encrypt, gen_cipher_key
from seaweedfs_tpu.utils.compression import (
    decompress,
    is_compressible,
    maybe_compress,
)


def test_compression_roundtrip_and_gating():
    text = b"the quick brown fox " * 500
    packed, did = maybe_compress(text, "text/plain")
    assert did and len(packed) < len(text)
    assert decompress(packed) == text
    # incompressible types pass through
    jpg, did = maybe_compress(text, "image/jpeg")
    assert not did and jpg == text
    # tiny payloads pass through
    small, did = maybe_compress(b"hi", "text/plain")
    assert not did
    # gzip frames are also readable (legacy volumes)
    import gzip

    assert decompress(gzip.compress(text)) == text
    assert is_compressible("application/json")
    assert not is_compressible("video/mp4")
    assert is_compressible("", ".css")


def test_cipher_roundtrip():
    key = gen_cipher_key()
    data = os.urandom(10_000)
    blob = encrypt(data, key)
    assert blob != data and len(blob) == len(data) + 12 + 16
    assert decrypt(blob, key) == data
    with pytest.raises(Exception):
        decrypt(blob, gen_cipher_key())  # wrong key must not decrypt
    # nonce is fresh per call -> different ciphertexts
    assert encrypt(data, key) != blob


def test_chunk_cache_lru_and_disk(tmp_path):
    cache = ChunkCache(mem_limit_bytes=1000, disk_dir=str(tmp_path / "cc"))
    cache.put("1,aa", b"x" * 400)
    cache.put("2,bb", b"y" * 400)
    assert cache.get("1,aa") == b"x" * 400
    cache.put("3,cc", b"z" * 400)  # evicts 2,bb from memory (LRU)
    assert "2,bb" not in cache._mem
    # ... but the disk tier still has it, and a get() promotes it back
    assert cache.get("2,bb") == b"y" * 400
    assert "2,bb" in cache._mem
    cache.invalidate("2,bb")
    assert cache.get("2,bb") is None
    # oversized entries are not cached
    cache.put("4,dd", b"w" * 10_000)
    assert cache.get("4,dd") is None


def test_image_resize_modes():
    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", (100, 60), "red").save(buf, format="PNG")
    png = buf.getvalue()

    def dims(b):
        return Image.open(io.BytesIO(b)).size

    assert dims(resized(png, width=50)) == (50, 30)
    assert dims(resized(png, height=30)) == (50, 30)
    assert dims(resized(png, width=40, height=40)) == (40, 40)  # exact
    assert dims(resized(png, width=40, height=40, mode="fit")) == (40, 24)
    assert dims(resized(png, width=40, height=40, mode="fill")) == (40, 40)
    # non-image data passes through untouched
    assert resized(b"not an image", width=10) == b"not an image"
    assert resized(png) == png  # no dims -> passthrough


def test_volume_read_resizes_images(tmp_path):
    from PIL import Image

    async def go():
        cluster = LocalCluster(base_dir=str(tmp_path), n_volume_servers=1)
        await cluster.start()
        try:
            from seaweedfs_tpu.operation import assign, upload_data

            buf = io.BytesIO()
            Image.new("RGB", (100, 60), "blue").save(buf, format="PNG")
            a = await assign(cluster.master.advertise_url)
            await upload_data(
                f"http://{a.url}/{a.fid}", buf.getvalue(), filename="p.png",
                mime="image/png",
            )
            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://{a.url}/{a.fid}?width=50") as r:
                    body = await r.read()
                    assert Image.open(io.BytesIO(body)).size == (50, 30)
                async with s.get(f"http://{a.url}/{a.fid}") as r:
                    body = await r.read()
                    assert Image.open(io.BytesIO(body)).size == (100, 60)
        finally:
            await cluster.stop()

    asyncio.run(go())


def test_filer_cipher_compress_e2e(tmp_path):
    """Write through an encrypting+compressing filer, read back whole and
    ranged; verify the stored volume bytes are NOT the plaintext."""

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, with_filer=True,
            filer_kwargs=dict(cipher=True, max_mb=1),
        )
        await cluster.start()
        try:
            base = f"http://{cluster.filer.url}"
            data = (b"A line of very compressible text.\n" * 40_000)  # ~1.3MB, 2 chunks
            async with aiohttp.ClientSession() as s:
                async with s.put(
                    base + "/enc/f.txt", data=data,
                    headers={"Content-Type": "text/plain"},
                ) as r:
                    assert r.status == 201
                async with s.get(base + "/enc/f.txt") as r:
                    assert await r.read() == data
                async with s.get(
                    base + "/enc/f.txt",
                    headers={"Range": "bytes=1048000-1049999"},
                ) as r:
                    assert r.status == 206
                    assert await r.read() == data[1048000:1050000]
            # chunks carry cipher keys + compression flag in metadata
            entry = cluster.filer.filer.find_entry("/enc/f.txt")
            assert entry.chunks and all(c.cipher_key for c in entry.chunks)
            assert all(c.is_compressed for c in entry.chunks)
            # raw .dat content must not contain the plaintext
            found = False
            for root, _, files in os.walk(str(tmp_path)):
                for f in files:
                    if f.endswith(".dat"):
                        found = True
                        from seaweedfs_tpu.utils.aiofile import (
                            read_file_bytes,
                        )

                        blob = await read_file_bytes(os.path.join(root, f))
                        assert b"A line of very compressible text." not in blob
            assert found, "no .dat volume files written?"
        finally:
            await cluster.stop()

    asyncio.run(go())


def test_exif_orientation_fix():
    """A JPEG tagged orientation=6 (rotate 90 CW) serves upright pixels
    after fix_orientation / inside the resize pipeline (reference
    images/orientation.go)."""
    from PIL import Image

    from seaweedfs_tpu.images.orientation import ORIENTATION_TAG, fix_orientation

    # 4x2 image: left half red, right half blue — distinctive per corner
    img = Image.new("RGB", (4, 2), (255, 0, 0))
    for x in range(2, 4):
        for y in range(2):
            img.putpixel((x, y), (0, 0, 255))
    exif = Image.Exif()
    exif[ORIENTATION_TAG] = 6  # stored rotated: viewer must rotate 90 CW
    buf = io.BytesIO()
    img.save(buf, format="JPEG", exif=exif, quality=100)
    rotated_jpeg = buf.getvalue()

    fixed = fix_orientation(rotated_jpeg)
    out = Image.open(io.BytesIO(fixed))
    assert out.size == (2, 4)  # dimensions swapped: pixels were turned
    assert out.getexif().get(ORIENTATION_TAG, 1) == 1
    # rotating 4x2 by 90 CW puts the original LEFT (red) half on TOP...
    # verify chroma ordering survived the turn (JPEG is lossy: compare hue)
    top = out.getpixel((0, 0))
    bottom = out.getpixel((0, 3))
    assert (top[0] > top[2]) != (bottom[0] > bottom[2])

    # the resize pipeline applies the same fix before scaling
    thumb = resized(rotated_jpeg, width=1)
    timg = Image.open(io.BytesIO(thumb))
    assert timg.size[0] == 1 and timg.size[1] == 2  # upright aspect 2:4

    # non-JPEG and normal-orientation payloads pass through untouched
    assert fix_orientation(b"not an image") == b"not an image"
    plain = io.BytesIO()
    img.save(plain, format="JPEG")
    assert fix_orientation(plain.getvalue()) == plain.getvalue()


def test_image_crop():
    """On-read crop (reference images/cropping.go): box honored, clamped,
    invalid boxes and non-images pass through."""
    import io

    import pytest

    PIL = pytest.importorskip("PIL")
    from PIL import Image

    from seaweedfs_tpu.images import cropped

    img = Image.new("RGB", (100, 80), (10, 20, 30))
    for x in range(50):
        for y in range(40):
            img.putpixel((x, y), (200, 0, 0))  # red top-left quadrant
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    data = buf.getvalue()

    out = cropped(data, 0, 0, 50, 40)
    got = Image.open(io.BytesIO(out))
    assert got.size == (50, 40)
    assert got.getpixel((10, 10)) == (200, 0, 0)

    # clamped to image bounds
    out = cropped(data, 60, 50, 500, 500)
    got = Image.open(io.BytesIO(out))
    assert got.size == (40, 30)
    assert got.getpixel((5, 5)) == (10, 20, 30)

    # invalid box / non-image: untouched
    assert cropped(data, 30, 30, 10, 10) == data
    assert cropped(b"not an image", 0, 0, 10, 10) == b"not an image"
