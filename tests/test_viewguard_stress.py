"""Viewguard-instrumented stress: zero-copy reads racing budget
eviction, vacuum/compaction, and in-flight DevicePipeline batches — the
runtime half of graftlint's GL109/GL110 dataflow rules.

Contracts:
  * guard semantics — a mutated-under-the-holder view, an arena reuse
    with outstanding exports, and a donated outstanding view all raise
    ViewGuardViolation; the clean patterns (release, slot-scoped arena
    exports, copies) stay quiet;
  * EC race — zero-copy batch reads of a degraded volume racing budget
    eviction stay byte-exact or fail a clean CacheMiss, never stale
    bytes, with every payload view verified at release;
  * vacuum race — a compaction that rewrites the .dat under outstanding
    zero-copy views leaves every one of them byte-stable (the pread
    `bytes` + refcounted old-fd design is what PROVES it, at the
    `vacuum.commit` verification hook).

All device work runs on the CPU test mesh (conftest); the EC stress
pins a DeviceShardCache exactly like the lockwatch stress does.
"""
import random
import threading
import time

import numpy as np
import pytest

import viewguard
from seaweedfs_tpu.ops import rs_resident
from seaweedfs_tpu.storage import ec
from seaweedfs_tpu.storage import vacuum as vacuum_mod
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume


def _make_volume(tmp_path, vid=31, count=24, seed=11):
    rng = random.Random(seed)
    v = Volume(str(tmp_path), vid)
    blobs = {}
    for i in range(1, count + 1):
        size = rng.choice([100, 1337, 4096, 70_000])
        data = rng.randbytes(size)
        cookie = rng.getrandbits(32)
        v.write(i, cookie, data, name=f"f{i}".encode())
        blobs[i] = (cookie, data)
    v.sync()
    return v, blobs


# ------------------------------------------------------- guard semantics


def test_guard_detects_mutation_under_outstanding_view():
    g = viewguard.ViewGuard()
    src = bytearray(b"stable bytes here")
    view = memoryview(src)[7:12]
    g.export(view, src, "window")
    src[8] ^= 0xFF  # scribble under the holder
    with pytest.raises(viewguard.ViewGuardViolation, match="changed"):
        g.release(view)


def test_guard_clean_release_and_copy():
    g = viewguard.ViewGuard()
    src = bytearray(b"stable bytes here")
    view = memoryview(src)[7:12]
    g.export(view, src, "window")
    g.release(view)
    src[0] ^= 0xFF  # mutation AFTER release is fine
    g.assert_clean()
    assert g.releases_total == 1


def test_guard_arena_reuse_with_outstanding_export_fails():
    with viewguard.watch() as g:
        arena = rs_resident.StagingArena(width=64)
        arena.stage_fused([1, 2, 3], 1)  # export outstanding
        with pytest.raises(viewguard.ViewGuardViolation, match="reuses"):
            arena.stage_fused([4, 5], 0)
    assert g.violations


def test_guard_slot_scoped_arena_exports_release_cleanly():
    with viewguard.watch() as g:
        pipe = rs_resident.DevicePipeline(slots=1)
        for _ in range(3):  # same arena reused across slots: clean
            with pipe.slot() as s:
                s.arena.stage_fused([7, 8, 9], 0)
        assert g.outstanding == 0
    g.assert_clean()
    assert g.exports_total == 3 and g.releases_total == 3


def test_guard_donation_of_outstanding_view_fails():
    with viewguard.watch() as g:
        arena = rs_resident.StagingArena(width=64)
        vec = arena.stage_fused([1], 0)
        with pytest.raises(viewguard.ViewGuardViolation, match="donates"):
            g.check_donation(vec, "jit call")
    # a fresh (untracked) array is not a donation hazard
    g.check_donation(np.zeros(4, dtype=np.int32), "jit call")


def test_guard_dispatch_boundary_rejects_live_export_on_cpu():
    """The wired enforcement: on a zero-copy PJRT client (the CPU test
    mesh), an outstanding arena export reaching the donated position of
    `_dispatch_call` fails BEFORE any device work — the regression
    guard for reconstruct_intervals' arena-gating invariant."""
    with viewguard.watch() as g:
        arena = rs_resident.StagingArena(width=64)
        vec = arena.stage_fused([1, 2], 0)
        with pytest.raises(viewguard.ViewGuardViolation, match="donates"):
            rs_resident._dispatch_call(
                "fused", vec, None, (), 0, 0, 1, 0, 0, "xla", True
            )
    assert g.violations


def test_guard_tracks_zero_copy_needle_parse():
    with viewguard.watch() as g:
        raw = Needle(id=0xBEE, cookie=3, data=b"z" * 500).to_bytes()
        n = Needle.from_bytes(raw, copy=False)
        assert g.outstanding == 1
        g.release(n.data)
        assert g.outstanding == 0
        # copying parse registers nothing
        Needle.from_bytes(raw, copy=True)
        assert g.outstanding == 0
    g.assert_clean()


def test_guard_catches_bytearray_scribble_at_exit():
    with viewguard.watch() as g:
        raw = bytearray(Needle(id=0xF00, cookie=1, data=b"q" * 256).to_bytes())
        n = Needle.from_bytes(raw, copy=False)
        assert isinstance(n.data, memoryview)
        raw[30] ^= 0xFF  # payload byte under the outstanding view
    with pytest.raises(viewguard.ViewGuardViolation, match="changed"):
        g.assert_clean()


# ---------------------------------------------------------- EC race


VID = 33
MISSING = 5


def test_zero_copy_ec_reads_race_eviction_under_viewguard(tmp_path):
    """Readers pull zero-copy batches through the device-resident
    reconstruct while an evictor cycles shards across the budget: every
    successful read is byte-exact (views verified at release), losses
    fail as clean CacheMiss, and no view ever reads drifted bytes."""
    v, blobs = _make_volume(tmp_path, vid=VID)
    base = Volume.base_name(v.dir, v.id, v.collection)
    ec.write_ec_files(base, backend="cpu")
    ec.write_sorted_file_from_idx(base)
    v.close()

    errors: list[BaseException] = []
    good_reads = 0
    clean_misses = 0
    stop = threading.Event()
    lock = threading.Lock()

    with viewguard.watch() as g:
        ev = ec.EcVolume(str(tmp_path), v.id)
        for sid in range(14):
            if sid != MISSING:
                ev.add_shard(sid)
        cache = rs_resident.DeviceShardCache(
            shard_quantum=1 << 20, layout="blockdiag"
        )
        cache.warm_sizes = ()  # CI convention: no AOT grid compile
        ev.load_shards_to_device(cache)
        per_shard = cache.bytes_used // 13
        cache.budget = per_shard * 12  # every re-pin evicts the LRU

        nids = sorted(blobs)

        def reader(seed: int):
            nonlocal good_reads, clean_misses
            rng = random.Random(seed)
            deadline = time.time() + 20
            mine = 0
            while time.time() < deadline and mine < 8:
                batch = rng.sample(nids, 3)
                try:
                    out = ev.read_needles_batch(
                        batch, backend="cpu", zero_copy=True
                    )
                except rs_resident.CacheMiss:
                    with lock:
                        clean_misses += 1
                    time.sleep(0.01)
                    continue
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                for nid, res in zip(batch, out):
                    if isinstance(res, rs_resident.CacheMiss):
                        with lock:
                            clean_misses += 1
                        continue
                    if isinstance(res, Exception):
                        errors.append(res)
                        return
                    want = blobs[nid][1]
                    if bytes(res.data) != want:
                        errors.append(
                            AssertionError(f"stale bytes for {nid}")
                        )
                        return
                    # done reading: verify-and-drop the exported view
                    if isinstance(res.data, memoryview):
                        g.release(res.data)
                mine += 1
                with lock:
                    good_reads += 1

        def evictor():
            i = 0
            sids = [s for s in range(14) if s != MISSING]
            while not stop.is_set():
                sid = sids[i % len(sids)]
                try:
                    cache.put(
                        VID, sid,
                        np.fromfile(ev.shards[sid].path, dtype=np.uint8),
                    )
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                i += 1

        threads = [
            threading.Thread(target=reader, args=(1,), name="reader"),
            threading.Thread(target=reader, args=(2,), name="reader2"),
            threading.Thread(target=evictor, name="evictor"),
        ]
        for t in threads:
            t.start()
        threads[0].join()
        threads[1].join()
        stop.set()
        threads[2].join()
        ev.close()

    assert not errors, errors
    assert good_reads > 0
    assert g.exports_total > 0, "no zero-copy views were ever tracked"
    g.assert_clean()


def test_sharded_zero_copy_reads_race_eviction_and_warm(tmp_path):
    """r19 mesh-layout race: readers pull zero-copy batches through the
    LANE-SHARDED reconstruct while an evictor cycles shards across the
    per-device budgets AND a warm thread keeps re-arming the sharded
    AOT plan.  Every successful read is byte-exact (views verified at
    release), losses are clean CacheMiss (ColdShape sheds included —
    the host path serves the same bytes), never stale bytes."""
    v, blobs = _make_volume(tmp_path, vid=VID)
    base = Volume.base_name(v.dir, v.id, v.collection)
    ec.write_ec_files(base, backend="cpu")
    ec.write_sorted_file_from_idx(base)
    v.close()

    errors: list[BaseException] = []
    good_reads = 0
    clean_misses = 0
    stop = threading.Event()
    lock = threading.Lock()

    with viewguard.watch() as g:
        ev = ec.EcVolume(str(tmp_path), v.id)
        for sid in range(14):
            if sid != MISSING:
                ev.add_shard(sid)
        cache = rs_resident.DeviceShardCache(
            shard_quantum=1 << 20, layout="blockdiag",
            mesh_devices=0, mesh_min_shard_bytes=0,
        )
        cache.warm_sizes = (4096,)
        cache.warm_counts = (4,)
        ev.load_shards_to_device(cache)
        assert cache.placement(VID) == "mesh"
        # per-device budget of 12 of the 13 pinned shards' chunks:
        # every re-pin crosses the per-device budgets and evicts the
        # LRU sharded entry on EVERY device at once
        cache.budget = (cache.bytes_used // 13) * 12

        nids = sorted(blobs)

        def reader(seed: int):
            nonlocal good_reads, clean_misses
            rng = random.Random(seed)
            deadline = time.time() + 20
            mine = 0
            while time.time() < deadline and mine < 8:
                batch = rng.sample(nids, 3)
                try:
                    out = ev.read_needles_batch(
                        batch, backend="cpu", zero_copy=True
                    )
                except rs_resident.CacheMiss:
                    with lock:
                        clean_misses += 1
                    time.sleep(0.01)
                    continue
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                for nid, res in zip(batch, out):
                    if isinstance(res, rs_resident.CacheMiss):
                        with lock:
                            clean_misses += 1
                        continue
                    if isinstance(res, Exception):
                        errors.append(res)
                        return
                    want = blobs[nid][1]
                    if bytes(res.data) != want:
                        errors.append(
                            AssertionError(f"stale bytes for {nid}")
                        )
                        return
                    if isinstance(res.data, memoryview):
                        g.release(res.data)
                mine += 1
                with lock:
                    good_reads += 1

        def evictor():
            i = 0
            sids = [s for s in range(14) if s != MISSING]
            while not stop.is_set():
                sid = sids[i % len(sids)]
                try:
                    cache.put(
                        VID, sid,
                        np.fromfile(ev.shards[sid].path, dtype=np.uint8),
                    )
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                i += 1

        def warmer():
            while not stop.is_set():
                try:
                    rs_resident.warm(
                        cache, VID, sizes=cache.warm_sizes,
                        counts=cache.warm_counts, aot=True, wait=False,
                    )
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                time.sleep(0.05)

        threads = [
            threading.Thread(target=reader, args=(5,), name="s-reader"),
            threading.Thread(target=reader, args=(6,), name="s-reader2"),
            threading.Thread(target=evictor, name="s-evictor"),
            threading.Thread(target=warmer, name="s-warmer"),
        ]
        for t in threads:
            t.start()
        threads[0].join()
        threads[1].join()
        stop.set()
        threads[2].join()
        threads[3].join()
        ev.close()

    assert not errors, errors
    assert good_reads > 0
    assert g.exports_total > 0, "no zero-copy views were ever tracked"
    g.assert_clean()


# ------------------------------------------- tier promote/demote race


def test_zero_copy_reads_race_tier_promotion_demotion(tmp_path):
    """r15 ladder race: readers pull zero-copy batches while a tiering
    controller flips two volumes between HBM, the host-RAM tier, and
    disk (budget fits only one volume, hysteresis disabled so every
    flip is a promote+demote pair).  Demotion routes through the
    claim/evict release path and host staging, so every successful read
    is byte-exact (views — over reconstruct output AND host-tier
    arrays — verified at release) and losses are clean CacheMiss, never
    stale bytes."""
    from seaweedfs_tpu.ops.rs_resident import DeviceShardCache
    from seaweedfs_tpu.serving import ServingConfig
    from seaweedfs_tpu.serving.tiering import TieringController
    from seaweedfs_tpu.storage.disk_location import DiskLocation
    from seaweedfs_tpu.storage.store import Store

    vids = (61, 62)
    blobs = {}
    for vid in vids:
        v, vol_blobs = _make_volume(tmp_path, vid=vid, count=10, seed=vid)
        base = Volume.base_name(v.dir, v.id, v.collection)
        ec.write_ec_files(base, backend="cpu")
        ec.write_sorted_file_from_idx(base)
        v.close()
        import os

        for ext in (".dat", ".idx"):
            if os.path.exists(base + ext):
                os.remove(base + ext)
        blobs[vid] = vol_blobs

    errors: list[BaseException] = []
    good_reads = 0
    clean_misses = 0
    stop = threading.Event()
    lock = threading.Lock()

    with viewguard.watch() as g:
        store = Store([DiskLocation(str(tmp_path))])
        cache = DeviceShardCache(shard_quantum=1 << 20, layout="blockdiag")
        cache.warm_sizes = ()  # CI convention: no AOT grid compile
        evs = {}
        for vid in vids:
            store.mount_ec_shards(vid, list(range(14)))
            ev = store.find_ec_volume(vid)
            ev.device_cache = cache
            # degrade each volume differently so batch reads exercise
            # the device/host reconstruct, not just local preads
            ev.shards.pop(vid % 14).close()
            evs[vid] = ev
        # cache attached AFTER the mounts: the controller owns every
        # placement (no mount-time pin threads racing the ladder)
        store.ec_device_cache = cache
        ctl = TieringController(
            store,
            ServingConfig(
                tier_host_cache_mb=64,
                tier_min_residency_seconds=0.0,
                tier_promote_ratio=1.0,
                tier_interval_seconds=0.0,
            ).validated(),
        )
        ev0 = evs[vids[0]]
        cache.budget = len(ev0.shards) * cache._padded_len(ev0.shard_size)

        def reader(seed: int):
            nonlocal good_reads, clean_misses
            rng = random.Random(seed)
            deadline = time.time() + 30
            # read until the mover finished its flips (stop) so every
            # promotion/demotion races live zero-copy reads
            while time.time() < deadline and not stop.is_set():
                vid = vids[rng.random() > 0.5]
                nids = rng.sample(sorted(blobs[vid]), 3)
                try:
                    out = evs[vid].read_needles_batch(
                        nids, backend="cpu", zero_copy=True
                    )
                except rs_resident.CacheMiss:
                    with lock:
                        clean_misses += 1
                    time.sleep(0.005)
                    continue
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                for nid, res in zip(nids, out):
                    if isinstance(res, rs_resident.CacheMiss):
                        with lock:
                            clean_misses += 1
                        continue
                    if isinstance(res, Exception):
                        errors.append(res)
                        return
                    if bytes(res.data) != blobs[vid][nid][1]:
                        errors.append(
                            AssertionError(f"stale bytes for {vid}/{nid}")
                        )
                        return
                    if isinstance(res.data, memoryview):
                        g.release(res.data)
                with lock:
                    good_reads += 1

        def mover():
            try:
                for flip in range(6):
                    hot = vids[flip % 2]
                    for v in vids:
                        ctl.heat.forget(v)
                    for _ in range(10):
                        ctl.note_read(hot)
                    ctl.rebalance()
                    time.sleep(0.05)  # let reads land between flips
            except BaseException as e:  # noqa: BLE001 — collected
                errors.append(e)
            finally:
                stop.set()

        threads = [
            threading.Thread(target=reader, args=(1,), name="tier-reader"),
            threading.Thread(target=reader, args=(2,), name="tier-reader2"),
            threading.Thread(target=mover, name="tier-mover"),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        promos = sum(ctl.promotions.values())
        demos = sum(ctl.demotions.values())
        for ev in evs.values():
            ev.close()
        store.close()

    assert not errors, errors
    assert good_reads > 0
    # the race actually raced: the ladder moved under the readers
    assert promos >= 2 and demos >= 1, (promos, demos)
    assert g.exports_total > 0
    g.assert_clean()


# -------------------------------------------------------- vacuum race


def test_vacuum_rewrite_keeps_outstanding_views_byte_stable(tmp_path):
    """Hold zero-copy views over live needles while vacuum compacts the
    volume (twice, with deletes in between): the commit-time guard hook
    re-verifies every outstanding view, and every held view still reads
    its original bytes afterwards."""
    v, blobs = _make_volume(tmp_path, vid=41, count=16)
    with viewguard.watch() as g:
        held = []
        for nid in sorted(blobs)[:6]:
            n = v.read(nid, cookie=blobs[nid][0], zero_copy=True)
            assert isinstance(n.data, memoryview)
            held.append((nid, n))
        # create garbage, then compact UNDER the outstanding views
        for nid in sorted(blobs)[10:]:
            v.delete(nid, cookie=blobs[nid][0])
        assert vacuum_mod.vacuum(v) > 0
        # second cycle: delete some of the very needles being held
        for nid, _ in held[:2]:
            v.delete(nid, cookie=blobs[nid][0])
        vacuum_mod.vacuum(v)
        for nid, n in held:
            assert bytes(n.data) == blobs[nid][1], f"needle {nid} drifted"
            g.release(n.data)
        # post-vacuum reads still serve the survivors byte-exact
        for nid in sorted(blobs)[6:10]:
            n = v.read(nid, cookie=blobs[nid][0], zero_copy=True)
            assert bytes(n.data) == blobs[nid][1]
            g.release(n.data)
    g.assert_clean()
    v.close()


def test_concurrent_vacuum_vs_zero_copy_readers(tmp_path):
    """Threaded race: readers stream zero-copy views while a vacuum
    thread compacts repeatedly; every read is byte-exact and the guard
    verifies every view at release and at each commit."""
    v, blobs = _make_volume(tmp_path, vid=43, count=20)
    live = sorted(blobs)[:12]
    for nid in sorted(blobs)[12:]:
        v.delete(nid, cookie=blobs[nid][0])

    errors: list[BaseException] = []
    stop = threading.Event()
    reads = 0
    lock = threading.Lock()

    with viewguard.watch() as g:
        def reader(seed: int):
            nonlocal reads
            rng = random.Random(seed)
            while not stop.is_set():
                nid = rng.choice(live)
                try:
                    n = v.read(nid, cookie=blobs[nid][0], zero_copy=True)
                    time.sleep(0.001)  # hold the view across the race
                    if bytes(n.data) != blobs[nid][1]:
                        errors.append(AssertionError(f"drift on {nid}"))
                        return
                    g.release(n.data)
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)
                    return
                with lock:
                    reads += 1

        def vacuumer():
            try:
                for _ in range(5):
                    vacuum_mod.vacuum(v)
                    time.sleep(0.01)
            except BaseException as e:  # noqa: BLE001 — collected
                errors.append(e)

        threads = [
            threading.Thread(target=reader, args=(1,)),
            threading.Thread(target=reader, args=(2,)),
            threading.Thread(target=vacuumer),
        ]
        for t in threads:
            t.start()
        threads[2].join()
        stop.set()
        threads[0].join()
        threads[1].join()

    assert not errors, errors
    assert reads > 0
    g.assert_clean()
    v.close()
