"""volume.fsck: filer<->volume cross-check with orphan purge
(reference: weed/shell/command_volume_fsck.go)."""
import asyncio
import io

import aiohttp
import pytest

from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.shell import CommandEnv, run_command


def test_volume_fsck(tmp_path):
    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, with_filer=True
        )
        await cluster.start()
        try:
            env = CommandEnv([cluster.master.advertise_url], out=io.StringIO())
            await run_command(env, "lock")
            deadline = asyncio.get_event_loop().time() + 10
            while True:
                try:
                    await env.find_filer()
                    break
                except RuntimeError:
                    if asyncio.get_event_loop().time() > deadline:
                        pytest.fail("filer never registered")
                    await asyncio.sleep(0.1)

            base = f"http://{cluster.filer.url}"
            async with aiohttp.ClientSession() as s:
                await s.put(base + "/keep/one.bin", data=b"k" * 5000)
                await s.put(base + "/keep/two.bin", data=b"t" * 5000)

            # a clean tree: no orphans, no broken references
            await run_command(env, "volume.fsck -cutoffMinutes 0")
            out = env.out.getvalue()
            assert "0 orphan needles" in out and "0 broken references" in out

            # orphan: blob written directly to a volume, no filer entry
            from seaweedfs_tpu.operation import assign, upload_data

            a = await assign(cluster.master.advertise_url)
            await upload_data(f"http://{a.url}/{a.fid}", b"orphan blob")
            # fresh needles are protected by the recency cutoff...
            await run_command(env, "volume.fsck -reallyDeleteFromVolume")
            assert "recent, skipped" in env.out.getvalue()
            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://{a.url}/{a.fid}") as r:
                    assert r.status == 200, "cutoff must protect fresh needles"
            # ...and only counted/purged when the cutoff allows
            await run_command(env, "volume.fsck -cutoffMinutes 0")
            assert "1 orphan needles" in env.out.getvalue()
            await run_command(
                env, "volume.fsck -reallyDeleteFromVolume -cutoffMinutes 0"
            )
            assert "(1 purged)" in env.out.getvalue()
            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://{a.url}/{a.fid}") as r:
                    assert r.status == 404, "orphan must be gone"
            await run_command(env, "volume.fsck -cutoffMinutes 0")
            assert "0 orphan needles" in env.out.getvalue().splitlines()[-1]

            # broken reference: delete a chunk behind the filer's back
            entry = cluster.filer.filer.find_entry("/keep/one.bin")
            fid = entry.chunks[0].file_id
            async with aiohttp.ClientSession() as s:
                await s.delete(f"http://{a.url}/{fid}")
            await run_command(env, "volume.fsck -cutoffMinutes 0")
            assert "1 broken references" in env.out.getvalue().splitlines()[-1]
        finally:
            await cluster.stop()

    asyncio.run(go())
