"""Volume-scale EC encode proof (VERDICT r4 next-round #3): an >=11GB
`.dat` goes through the REAL write_ec_files pipeline so genuine 1GB
large-block rows exist (layout.py:17), with

  * shard sizes matching the layout math (one 1GB large row + small rows),
  * sampled-interval byte-equivalence of data AND parity shards against
    the numpy oracle,
  * a mounted degraded read whose needle record CROSSES the
    large-row/small-row boundary, reconstructing from 10 survivors,
  * bounded staging memory (the 3-deep 40MB pipeline, not the volume).

The volume is sparse (holes read as zeros; the encoder's sparse-aware
shard writes keep the outputs sparse too), so the test costs ~seconds of
real IO while the offsets, interval math, 4-byte needle-map offsets and
the two-phase encode loop all run at true 11GB scale — the part
scaled-down unit tests could never exercise.  Reference layout being
matched: weed/storage/erasure_coding/ec_encoder.go:194-231, ec_locate.go.
"""
import os
import resource

import numpy as np
import pytest

from seaweedfs_tpu.ops import rs, rs_cpu
from seaweedfs_tpu.storage import needle as needle_mod
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import encoder, layout
from seaweedfs_tpu.storage.ec.volume import EcVolume
from seaweedfs_tpu.storage.volume_info import save_volume_info

GB = 1 << 30
MB = 1 << 20


@pytest.mark.skipif(
    not rs_cpu.native_available(),
    reason="needs the native CPU kernel (numpy would take minutes at 11GB)",
)
def test_volume_scale_encode_11gb(tmp_path):
    dat_size = 11 * GB + 5 * MB
    vid = 1
    base = str(tmp_path / str(vid))
    rng = np.random.default_rng(42)

    # ---- craft a sparse 11GB volume with needles at probing offsets
    boundary = 10 * GB  # one full large row (10 x 1GB), then small rows
    needles = []  # (id, offset, record bytes)
    specs = [
        (0x101, 5 * GB + 98760, 8192),        # deep inside the large row
        (0x102, boundary - 4096, 12000),      # record CROSSES the boundary
        (0x103, 10 * GB + 513 * MB + 64, 4096),  # small-row region
    ]
    with open(base + ".dat", "wb") as f:
        f.truncate(dat_size)
        for nid, off, body in specs:
            n = needle_mod.Needle(
                id=nid, cookie=0xABCD,
                data=rng.integers(0, 256, body, dtype=np.uint8).tobytes(),
            )
            rec = n.to_bytes()
            assert off % t.NEEDLE_PADDING_SIZE == 0
            os.pwrite(f.fileno(), rec, off)
            needles.append((nid, off, n.size, rec, n.data))
    save_volume_info(base + ".vif", {"version": needle_mod.CURRENT_VERSION})
    # sorted .ecx: key(8B BE) + offset(4B, 8-byte units) + size(4B BE)
    with open(base + ".ecx", "wb") as f:
        for nid, off, size, _, _ in sorted(needles):
            f.write(
                nid.to_bytes(8, "big")
                + t.offset_to_bytes(off)
                + size.to_bytes(4, "big", signed=True)
            )

    # ---- encode through the real pipeline, with memory tracked
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    stats: dict = {}
    encoded = encoder.write_ec_files(base, backend="native", stats=stats)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    assert encoded == dat_size
    # staging is the 3-deep pipeline of [10, 4MB] batches (~120MB), not
    # the volume; allow slack for allocator behavior but far below 11GB
    assert (rss_after - rss_before) * 1024 < 600 * MB, (
        f"encode staging ballooned: {(rss_after - rss_before) / 1024:.0f}MB"
    )
    assert stats["batches"] == 256 + 103  # 1GB row in 4MB strides + small rows

    want_shard = layout.shard_file_size(dat_size)
    assert want_shard == 1 * GB + 103 * MB  # real large blocks existed
    for sid in range(layout.TOTAL_SHARDS):
        assert os.path.getsize(base + layout.to_ext(sid)) == want_shard

    # ---- sampled-interval byte-equivalence: every needle's data-shard
    # intervals reassemble to the original record
    for nid, off, size, rec, _ in needles:
        total = needle_mod.actual_size(size, needle_mod.CURRENT_VERSION)
        intervals = layout.locate_data(dat_size, off, total)
        got = bytearray()
        for iv in intervals:
            sid, soff = iv.to_shard_and_offset()
            with open(base + layout.to_ext(sid), "rb") as f:
                got += os.pread(f.fileno(), iv.size, soff)
        assert bytes(got[: len(rec)]) == rec, f"needle {nid:x} intervals"
    # the boundary needle really crossed phases
    total = needle_mod.actual_size(specs[1][2], needle_mod.CURRENT_VERSION)
    ivs = layout.locate_data(dat_size, specs[1][1], total)
    assert any(iv.is_large_block for iv in ivs) and any(
        not iv.is_large_block for iv in ivs
    ), "boundary needle did not cross the large/small row boundary"

    # ---- parity oracle: sample windows in the large row AND a small row,
    # recompute parity with the numpy oracle from the data shards' bytes
    codec = rs.RSCodec(backend="numpy")
    for sample_off, width in [
        (specs[0][1] % GB & ~0xFFF, 4096),       # large row, needle region
        (0, 4096),                                # large row, hole region
        (1 * GB + 33 * MB, 4096),                 # small-row region
    ]:
        stack = np.zeros((10, width), dtype=np.uint8)
        for i in range(10):
            with open(base + layout.to_ext(i), "rb") as f:
                stack[i] = np.frombuffer(
                    os.pread(f.fileno(), width, sample_off), dtype=np.uint8
                )
        parity = codec.encode(stack)
        for j in range(4):
            with open(base + layout.to_ext(10 + j), "rb") as f:
                got = np.frombuffer(
                    os.pread(f.fileno(), width, sample_off), dtype=np.uint8
                )
            assert np.array_equal(got, parity[j]), (
                f"parity shard {10 + j} mismatch at {sample_off}"
            )

    # ---- mounted degraded read across the boundary: destroy the two
    # shards holding the boundary needle's head, reconstruct from 10
    sids_needed = sorted(
        {iv.to_shard_and_offset()[0] for iv in ivs}
    )
    victim = sids_needed[0]
    other = next(s for s in range(10) if s != victim)
    for sid in (victim, other):
        os.remove(base + layout.to_ext(sid))
    ev = EcVolume(str(tmp_path), vid)
    try:
        for sid in range(layout.TOTAL_SHARDS):
            if sid not in (victim, other):
                ev.add_shard(sid)
        n = ev.read_needle(specs[1][0], cookie=0xABCD, backend="native")
        assert n.data == needles[1][4], "degraded boundary read corrupt"
        # and a plain large-row needle too
        n = ev.read_needle(specs[0][0], backend="native")
        assert n.data == needles[0][4]
    finally:
        ev.close()
