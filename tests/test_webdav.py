"""WebDAV gateway e2e against an in-process cluster: PROPFIND listings,
PUT/GET round-trips with range reads, MKCOL, MOVE/COPY with Overwrite
semantics, DELETE, and class-2 LOCK/UNLOCK.

Reference behavior: weed/server/webdav_server.go (filer-backed
webdav.FileSystem); the protocol assertions follow RFC 4918.
"""
import asyncio
import os
import xml.etree.ElementTree as ET

import aiohttp
import pytest

from seaweedfs_tpu.server.cluster import LocalCluster

DAV = "{DAV:}"


def run(coro):
    return asyncio.run(coro)


async def make_cluster(tmp_path):
    cluster = LocalCluster(
        base_dir=str(tmp_path), n_volume_servers=1, with_webdav=True
    )
    await cluster.start()
    return cluster


async def req(session, method, url, **kw):
    async with session.request(method, url, **kw) as r:
        return r.status, dict(r.headers), await r.read()


def hrefs(body: bytes) -> list[str]:
    tree = ET.fromstring(body)
    return [
        resp.find(f"{DAV}href").text for resp in tree.findall(f"{DAV}response")
    ]


def test_webdav_roundtrip(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        base = f"http://{cluster.webdav.url}"
        try:
            async with aiohttp.ClientSession() as s:
                # OPTIONS advertises class 1+2
                st, hdr, _ = await req(s, "OPTIONS", base + "/")
                assert st == 200 and "2" in hdr["DAV"]

                # MKCOL + nested file PUT/GET
                st, _, _ = await req(s, "MKCOL", base + "/docs")
                assert st == 201
                st, _, _ = await req(s, "MKCOL", base + "/docs")
                assert st == 405, "MKCOL on existing collection"
                st, _, _ = await req(s, "MKCOL", base + "/no/parent")
                assert st == 409, "MKCOL without parent"

                data = os.urandom(300_000)
                st, _, _ = await req(s, "PUT", base + "/docs/a.bin", data=data)
                assert st == 201
                st, _, body = await req(s, "GET", base + "/docs/a.bin")
                assert st == 200 and body == data
                st, _, body = await req(
                    s, "GET", base + "/docs/a.bin",
                    headers={"Range": "bytes=1000-1999"},
                )
                assert st == 206 and body == data[1000:2000]

                # PUT over existing -> 204
                st, _, _ = await req(s, "PUT", base + "/docs/a.bin", data=b"x")
                assert st == 204
                st, _, body = await req(s, "GET", base + "/docs/a.bin")
                assert body == b"x"

                # PROPFIND depth 1 lists the collection + children
                st, _, body = await req(
                    s, "PROPFIND", base + "/docs", headers={"Depth": "1"}
                )
                assert st == 207
                found = hrefs(body)
                assert "/docs/" in found and "/docs/a.bin" in found
                # depth 0 only lists the collection itself
                st, _, body = await req(
                    s, "PROPFIND", base + "/docs", headers={"Depth": "0"}
                )
                assert hrefs(body) == ["/docs/"]
                st, _, _ = await req(s, "PROPFIND", base + "/gone")
                assert st == 404

                # content length is reported
                await req(s, "PUT", base + "/docs/b.bin", data=b"y" * 1234)
                st, _, body = await req(
                    s, "PROPFIND", base + "/docs/b.bin", headers={"Depth": "0"}
                )
                assert b"1234" in body

                # COPY then MOVE with Overwrite: F
                st, _, _ = await req(
                    s, "COPY", base + "/docs/b.bin",
                    headers={"Destination": base + "/docs/c.bin"},
                )
                assert st == 201
                st, _, _ = await req(
                    s, "MOVE", base + "/docs/c.bin",
                    headers={"Destination": base + "/docs/b.bin", "Overwrite": "F"},
                )
                assert st == 412, "Overwrite: F must refuse to clobber"
                st, _, _ = await req(
                    s, "MOVE", base + "/docs/c.bin",
                    headers={"Destination": base + "/docs/d.bin"},
                )
                assert st == 201
                st, _, body = await req(s, "GET", base + "/docs/d.bin")
                assert body == b"y" * 1234
                st, _, _ = await req(s, "GET", base + "/docs/c.bin")
                assert st == 404

                # collection COPY copies children
                st, _, _ = await req(
                    s, "COPY", base + "/docs",
                    headers={"Destination": base + "/backup"},
                )
                assert st == 201
                st, _, body = await req(s, "GET", base + "/backup/d.bin")
                assert st == 200 and body == b"y" * 1234

                # DELETE recursive
                st, _, _ = await req(s, "DELETE", base + "/backup")
                assert st == 204
                st, _, _ = await req(s, "GET", base + "/backup/d.bin")
                assert st == 404
        finally:
            await cluster.stop()

    run(go())


def test_webdav_propfind_depth_infinity(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        base = f"http://{cluster.webdav.url}"
        try:
            async with aiohttp.ClientSession() as s:
                await req(s, "MKCOL", base + "/a")
                await req(s, "MKCOL", base + "/a/b")
                await req(s, "PUT", base + "/a/b/deep.txt", data=b"d")
                st, _, body = await req(
                    s, "PROPFIND", base + "/a",
                    headers={"Depth": "infinity"},
                )
                assert st == 207
                found = hrefs(body)
                assert "/a/b/deep.txt" in found, found
                # depth 1 must NOT include grandchildren
                st, _, body = await req(
                    s, "PROPFIND", base + "/a", headers={"Depth": "1"}
                )
                assert "/a/b/deep.txt" not in hrefs(body)
        finally:
            await cluster.stop()

    run(go())


def test_webdav_locks(tmp_path):
    async def go():
        cluster = await make_cluster(tmp_path)
        base = f"http://{cluster.webdav.url}"
        try:
            async with aiohttp.ClientSession() as s:
                await req(s, "PUT", base + "/f.txt", data=b"v1")
                st, hdr, body = await req(s, "LOCK", base + "/f.txt")
                assert st == 200
                token = hdr["Lock-Token"].strip("<>")
                assert b"locktoken" in body

                # write without the token is refused; with it, allowed
                st, _, _ = await req(s, "PUT", base + "/f.txt", data=b"v2")
                assert st == 423
                st, _, _ = await req(
                    s, "PUT", base + "/f.txt", data=b"v2",
                    headers={"If": f"(<{token}>)"},
                )
                assert st == 204

                st, _, _ = await req(
                    s, "UNLOCK", base + "/f.txt",
                    headers={"Lock-Token": f"<{token}>"},
                )
                assert st == 204
                st, _, _ = await req(s, "PUT", base + "/f.txt", data=b"v3")
                assert st == 204, "unlocked file writable again"
        finally:
            await cluster.stop()

    run(go())
