"""Zero-copy read-path suite (r13): the memoryview parse must be
byte-identical to the copying parse at every layer — unit (Needle),
e2e whole-needle, range, and degraded (reconstructed) HTTP reads — and
the zero-copy route must keep response_copy_bytes_total at exactly 0.
Plus the slow-client guard: a dribbling reader is disconnected inside
its stall budget instead of holding the response open."""
import asyncio
import time

import aiohttp
import pytest

from seaweedfs_tpu import stats
from seaweedfs_tpu.storage.needle import CrcError, Needle


def run(coro):
    return asyncio.run(coro)


def _copy_bytes():
    return stats.REGISTRY.get_sample_value(
        "SeaweedFS_volumeServer_response_copy_bytes_total"
    ) or 0.0


# ------------------------------------------------------------------- unit


def test_from_bytes_zero_copy_equals_copying():
    n = Needle(
        id=0xABC, cookie=7, data=b"payload" * 100, name=b"f.bin",
        mime=b"application/x-thing", last_modified=1700000000,
        pairs=b'{"k":"v"}',
    )
    raw = n.to_bytes()
    a = Needle.from_bytes(raw)
    b = Needle.from_bytes(raw, copy=False)
    assert isinstance(a.data, bytes) and isinstance(b.data, memoryview)
    assert bytes(b.data) == a.data
    for attr in ("id", "cookie", "name", "mime", "pairs", "last_modified",
                 "checksum", "flags", "size"):
        assert getattr(a, attr) == getattr(b, attr), attr
    # the view really aliases the source buffer (no hidden copy)
    assert b.data.obj is raw


def test_from_bytes_zero_copy_over_bytearray_and_crc():
    n = Needle(id=1, cookie=2, data=b"x" * 1000)
    raw = bytearray(n.to_bytes())
    m = Needle.from_bytes(raw, copy=False)
    assert bytes(m.data) == b"x" * 1000
    # the corruption below is DELIBERATE: under a SWFS_VIEWGUARD sweep,
    # release the export first so the sanitizer doesn't (correctly!)
    # flag this fixture as a stale-byte serve
    import viewguard

    vg = viewguard.current()
    if vg is not None:
        vg.release(m.data)
    raw[20] ^= 0xFF  # corrupt the payload under the view
    with pytest.raises(CrcError):
        Needle.from_bytes(bytes(raw))


def test_from_bytes_zero_copy_tombstone_and_v1():
    t = Needle(id=5, cookie=0, size=-1)
    import struct

    hdr = struct.pack(">IQi", 0, 5, -1)
    parsed = Needle.from_bytes(hdr, copy=False)
    assert parsed.size == -1 and parsed.data == b""
    v1 = Needle(id=9, cookie=1, data=b"abc")
    raw1 = v1.to_bytes(version=1)
    p1 = Needle.from_bytes(raw1, version=1, copy=False)
    assert isinstance(p1.data, memoryview) and bytes(p1.data) == b"abc"
    assert t.size == -1


# ------------------------------------------------------------ e2e serving


def test_zero_copy_http_reads_byte_equal_and_copyless(tmp_path):
    """Whole-needle, range, and degraded (every read here reconstructs:
    two shards are destroyed) HTTP reads must be byte-identical between
    the zero-copy and the copying path — and the zero-copy route must
    add exactly 0 to response_copy_bytes_total while the copying route
    visibly pays."""
    from bench import build_degraded_cluster

    async def go():
        cluster, vs, blobs, _vid = await build_degraded_cluster(
            str(tmp_path), n_blobs=6, device_cache=True,
            cache_budget=1 << 30, warm_sizes=(),
        )
        try:
            cfg = vs.ec_dispatcher.cfg
            fid = next(iter(blobs))
            want = blobs[fid]
            results = {}
            async with aiohttp.ClientSession() as sess:
                for mode in ("zero_copy", "copying"):
                    cfg.zero_copy = mode == "zero_copy"
                    c0 = _copy_bytes()
                    whole, ranged = {}, {}
                    for f, data in blobs.items():
                        async with sess.get(f"http://{vs.url}/{f}") as r:
                            assert r.status == 200
                            whole[f] = await r.read()
                        lo, hi = 100, min(900, len(data) - 1)
                        async with sess.get(
                            f"http://{vs.url}/{f}",
                            headers={"Range": f"bytes={lo}-{hi}"},
                        ) as r:
                            assert r.status == 206, r.status
                            assert r.headers["Content-Range"] == (
                                f"bytes {lo}-{hi}/{len(data)}"
                            )
                            ranged[f] = (lo, hi, await r.read())
                    # suffix range: last N bytes, spec-valid Content-Range
                    async with sess.get(
                        f"http://{vs.url}/{fid}",
                        headers={"Range": "bytes=-64"},
                    ) as r:
                        assert r.status == 206
                        assert await r.read() == want[-64:]
                        assert r.headers["Content-Range"] == (
                            f"bytes {len(want) - 64}-{len(want) - 1}"
                            f"/{len(want)}"
                        )
                    # unsatisfiable range: 416, never an empty 206
                    async with sess.get(
                        f"http://{vs.url}/{fid}",
                        headers={
                            "Range": f"bytes={len(want) + 5}-{len(want) + 9}"
                        },
                    ) as r:
                        assert r.status == 416
                        assert r.headers["Content-Range"] == (
                            f"bytes */{len(want)}"
                        )
                    results[mode] = (whole, ranged, _copy_bytes() - c0)
            zc_whole, zc_rng, zc_copied = results["zero_copy"]
            cp_whole, cp_rng, cp_copied = results["copying"]
            for f, data in blobs.items():
                assert zc_whole[f] == data  # degraded read, byte-exact
                assert cp_whole[f] == data
                lo, hi, body = zc_rng[f]
                assert body == data[lo : hi + 1]
                assert zc_rng[f] == cp_rng[f]
            assert zc_copied == 0, (
                f"zero-copy route copied {zc_copied} bytes"
            )
            assert cp_copied > 0
            assert fid and want  # coverage fixture sanity
        finally:
            await cluster.stop()
            from seaweedfs_tpu.pb.rpc import close_all_channels

            await close_all_channels()

    run(go())


# --------------------------------------------------------- slow-client guard


def test_dribbling_client_releases_server_resources_at_budget(tmp_path):
    """A reader draining an 8MB body at a dribble must stop costing the
    SERVER anything once the per-response stall budget lapses: the
    handler aborts (response_stall_aborts_total +1) and the download
    byte-lease goes back to 0 while the dribbler is still dribbling —
    it can keep draining kernel-buffered TCP data, but no handler, no
    lease, and no needle buffer are held for it.  A concurrent fast
    reader is served byte-exact throughout."""
    from seaweedfs_tpu.operation import assign, upload_data
    from seaweedfs_tpu.server.cluster import LocalCluster

    async def go():
        cluster = LocalCluster(
            base_dir=str(tmp_path), n_volume_servers=1, pulse_seconds=1,
        )
        await cluster.start()
        drib = None
        try:
            vs = cluster.volume_servers[0]
            cfg = vs.ec_dispatcher.cfg
            cfg.stall_budget_seconds = 1.0
            cfg.stall_min_rate_kbps = 1 << 20  # budget ≈ the base second
            # track the download byte-lease (LocalCluster leaves the
            # throttle off; the lease is the held-resource probe)
            vs.download_limiter.limit = 64 << 20
            payload = bytes(range(256)) * (32 * 1024)  # 8MB
            a = await assign(cluster.master.advertise_url)
            await upload_data(f"http://{a.url}/{a.fid}", payload)

            stalls0 = stats.REGISTRY.get_sample_value(
                "SeaweedFS_volumeServer_response_stall_aborts_total"
            ) or 0.0
            dribbling = asyncio.Event()

            async def dribble():
                reader, writer = await asyncio.open_connection(
                    vs.ip, vs.port
                )
                writer.write(
                    f"GET /{a.fid} HTTP/1.1\r\n"
                    f"Host: {vs.url}\r\nConnection: close\r\n\r\n".encode()
                )
                await writer.drain()
                got = 0
                try:
                    while True:
                        chunk = await reader.read(1024)
                        if not chunk:
                            break
                        got += len(chunk)
                        dribbling.set()
                        await asyncio.sleep(0.05)  # ~20KB/s
                except ConnectionResetError:
                    pass  # the stall guard aborted us: expected
                finally:
                    writer.close()
                return got

            drib = asyncio.create_task(dribble())
            await asyncio.wait_for(dribbling.wait(), timeout=30)
            # give the 1s budget time to lapse, then probe the server
            deadline = time.perf_counter() + 10
            while time.perf_counter() < deadline:
                stalls = stats.REGISTRY.get_sample_value(
                    "SeaweedFS_volumeServer_response_stall_aborts_total"
                )
                if stalls == stalls0 + 1 and vs.download_limiter.in_flight == 0:
                    break
                await asyncio.sleep(0.2)
            assert stats.REGISTRY.get_sample_value(
                "SeaweedFS_volumeServer_response_stall_aborts_total"
            ) == stalls0 + 1, "stall guard never fired"
            assert vs.download_limiter.in_flight == 0, (
                "dribbler still holds the download byte-lease"
            )
            assert not drib.done()  # ...while the client is STILL dribbling
            # bystander: served fully and byte-exact after the abort
            async with aiohttp.ClientSession() as sess:
                async with sess.get(f"http://{vs.url}/{a.fid}") as r:
                    assert r.status == 200
                    assert await r.read() == payload
        finally:
            if drib is not None:
                drib.cancel()
                try:
                    await drib
                except asyncio.CancelledError:
                    pass
            await cluster.stop()
            from seaweedfs_tpu.pb.rpc import close_all_channels

            await close_all_channels()

    run(go())
