"""E2e satellite for graftflow: a vacuum/compaction rewrites the volume
while a zero-copy streamed response over the OLD buffer is still
dribbling out to a slow client.  The response must be byte-stable (or
cleanly aborted) — never interleaved old/new bytes — because the
zero-copy design views immutable pread `bytes` and the commit swaps the
dat fd by reference (readers on the old inode drain via refcount).

Runs under viewguard: every server-side zero-copy payload view is
fingerprinted at parse and re-verified at each vacuum commit and at
watch exit, so a stale-byte serve fails HERE even if the client-side
byte comparison were somehow satisfied by luck.
"""
import asyncio
import os

import viewguard
from seaweedfs_tpu.operation.assign import assign
from seaweedfs_tpu.operation.upload import upload_data
from seaweedfs_tpu.server.cluster import LocalCluster
from seaweedfs_tpu.storage import vacuum as vacuum_mod


def run(coro):
    return asyncio.run(coro)


def test_vacuum_racing_streamed_zero_copy_response(tmp_path):
    async def go():
        cluster = LocalCluster(base_dir=str(tmp_path))
        await cluster.start()
        drib_task = None
        try:
            master = cluster.master.advertise_url
            vs = cluster.volume_servers[0]
            # big enough that _respond_needle streams it chunked
            # (>64KB) and the dribbler holds the response open long
            # enough for two vacuums to land mid-stream
            payload = os.urandom(1 << 20)
            a = await assign(master)
            await upload_data(f"http://{a.url}/{a.fid}", payload, "big.bin")
            vid = int(a.fid.split(",")[0])
            # garbage for the vacuum to reclaim: a second needle,
            # deleted right away
            b = await assign(master)
            while int(b.fid.split(",")[0]) != vid:
                b = await assign(master)
            await upload_data(
                f"http://{b.url}/{b.fid}", os.urandom(200_000), "junk.bin"
            )
            v = vs.store.find_volume(vid)
            assert v is not None
            assert vs.ec_dispatcher.cfg.zero_copy  # the path under test

            got = bytearray()
            streaming = asyncio.Event()

            async def dribble() -> None:
                reader, writer = await asyncio.open_connection(
                    vs.ip, vs.port
                )
                try:
                    writer.write(
                        f"GET /{a.fid} HTTP/1.1\r\nHost: {vs.url}\r\n"
                        "Connection: close\r\n\r\n".encode()
                    )
                    await writer.drain()
                    # consume headers
                    while True:
                        line = await reader.readline()
                        if line in (b"\r\n", b""):
                            break
                    while True:
                        chunk = await reader.read(32 * 1024)
                        if not chunk:
                            break
                        got.extend(chunk)
                        streaming.set()
                        await asyncio.sleep(0.02)  # ~1.6 MB/s dribble
                finally:
                    writer.close()

            drib_task = asyncio.ensure_future(dribble())
            await asyncio.wait_for(streaming.wait(), timeout=30)

            # two compactions UNDER the in-flight response: first
            # reclaims the junk needle, second re-proves idempotence
            await asyncio.to_thread(
                lambda: (
                    v.delete(int(b.fid.split(",")[1][:-8], 16)),
                    vacuum_mod.vacuum(v),
                    vacuum_mod.vacuum(v),
                )
            )
            await asyncio.wait_for(drib_task, timeout=120)
            drib_task = None
            # byte-stable: the streamed body is exactly the original
            # payload — no interleaved post-compaction bytes.  (A clean
            # abort would show as a short body and fail here loudly,
            # which the contract also allows us to catch and report.)
            assert bytes(got) == payload, (
                f"streamed body diverged: {len(got)} bytes vs "
                f"{len(payload)} expected"
            )
            # and the volume still serves byte-exact AFTER the race
            import aiohttp

            async with aiohttp.ClientSession() as sess:
                async with sess.get(f"http://{vs.url}/{a.fid}") as r:
                    assert r.status == 200
                    assert await r.read() == payload
        finally:
            if drib_task is not None:
                drib_task.cancel()
                try:
                    await drib_task
                except asyncio.CancelledError:
                    pass
            await cluster.stop()
            from seaweedfs_tpu.pb.rpc import close_all_channels

            await close_all_channels()

    with viewguard.watch() as g:
        run(go())
    assert g.exports_total > 0, "server never took the zero-copy parse"
    g.assert_clean()
