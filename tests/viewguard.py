"""Runtime view-lifetime sanitizer — the dynamic complement of
graftlint's static GL109 (view-escape) and GL110 (use-after-donate).

The zero-copy serving path (r13) hands memoryviews of needle source
buffers all the way into HTTP body writes, and the staging arenas (r11)
hand numpy views of reused pinned blocks into donated device calls.
Static analysis proves views don't ESCAPE; it cannot prove the bytes a
still-outstanding view reads are the bytes that were exported.  This
harness closes that gap at test time:

  * every zero-copy `Needle.from_bytes(copy=False)` payload view is
    registered with a content fingerprint at export;
  * every `StagingArena.stage_*` view is registered against its arena,
    and REUSING an arena (the next `stage_*` on it) while a previous
    export is still outstanding is a violation — that is exactly the
    aliasing scribble the two-slot pipeline exists to prevent;
  * arena exports auto-release when their `DevicePipeline` slot is
    returned (the device call that consumed them has completed);
  * `vacuum.commit` triggers an immediate re-verification of every
    outstanding view: a vacuum that mutated bytes under a live zero-copy
    response fails HERE, not as interleaved bytes on a client socket;
  * `release(view)` / watch-exit verify fingerprints: any drift means a
    stale-byte serve and raises ViewGuardViolation.

Usage:

    with viewguard.watch() as g:
        ... exercise zero-copy reads / vacuum / batches ...
    g.assert_clean()        # verifies every outstanding view too

Suite-wide sweep (opt-in, see tests/conftest.py):
    SWFS_VIEWGUARD=1 pytest tests/
"""
from __future__ import annotations

import contextlib
import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Iterator


class ViewGuardViolation(AssertionError):
    """A view outlived its buffer's reuse, or its bytes drifted."""


def _fingerprint(view: Any) -> int:
    """crc32 of the view's current bytes (cheap at test sizes)."""
    if isinstance(view, memoryview):
        return zlib.crc32(view)
    # numpy view (arena staging) — tobytes() copies, fine for tests
    return zlib.crc32(view.tobytes() if hasattr(view, "tobytes") else bytes(view))


@dataclass
class _Export:
    view: Any          # strong ref: id() stays valid while registered
    source_id: int     # id() of the buffer/arena the view derives from
    label: str
    crc: int


@dataclass
class ViewGuard:
    violations: list = field(default_factory=list)
    exports_total: int = 0
    releases_total: int = 0
    reuse_checks_total: int = 0
    _mu: threading.Lock = field(default_factory=threading.Lock)
    _exports: dict = field(default_factory=dict)  # id(view) -> _Export

    # ------------------------------------------------------- registration

    def export(self, view: Any, source: Any, label: str) -> None:
        with self._mu:
            self.exports_total += 1
            self._exports[id(view)] = _Export(
                view, id(source), label, _fingerprint(view)
            )

    def release(self, view: Any) -> None:
        """Verify-and-drop one export (call when the holder is done
        reading — response fully written, device call returned)."""
        with self._mu:
            exp = self._exports.pop(id(view), None)
        if exp is None:
            return
        self.releases_total += 1
        self._verify(exp)

    def release_source(self, source: Any) -> None:
        """Release every outstanding export derived from `source`."""
        sid = id(source)
        with self._mu:
            mine = [k for k, e in self._exports.items() if e.source_id == sid]
            exps = [self._exports.pop(k) for k in mine]
        for exp in exps:
            self.releases_total += 1
            self._verify(exp)

    # --------------------------------------------------------- enforcement

    def check_reuse(self, source: Any, what: str) -> None:
        """A guarded source is about to be reused/overwritten: any
        outstanding export over it is a use-after-reuse hazard."""
        sid = id(source)
        self.reuse_checks_total += 1
        with self._mu:
            live = [e for e in self._exports.values() if e.source_id == sid]
        for exp in live:
            self._fail(
                f"{what} while view {exp.label!r} is still outstanding — "
                "the holder would read scribbled bytes"
            )

    def check_donation(self, arr: Any, what: str) -> None:
        """An array is being donated to a device call: donating a
        still-outstanding exported view hands its memory to XLA."""
        with self._mu:
            exp = self._exports.get(id(arr))
        if exp is not None:
            self._fail(
                f"{what} donates view {exp.label!r} that is still "
                "outstanding — the kernel may alias its buffer as output"
            )

    def verify_outstanding(self, why: str) -> None:
        """Re-fingerprint every outstanding export (e.g. right after a
        vacuum commit): drift = stale bytes already served."""
        with self._mu:
            live = list(self._exports.values())
        for exp in live:
            self._verify(exp, why=why)

    # ------------------------------------------------------------ verdicts

    def _verify(self, exp: _Export, why: str = "release") -> None:
        try:
            now = _fingerprint(exp.view)
        except ValueError:
            # underlying buffer was resized/closed with the view live:
            # that is its own violation (BufferError normally guards it)
            self._fail(
                f"view {exp.label!r} lost its buffer before {why}"
            )
            return
        if now != exp.crc:
            self._fail(
                f"view {exp.label!r} bytes changed under the holder "
                f"(detected at {why}): exported crc {exp.crc:08x}, now "
                f"{now:08x} — stale/interleaved bytes would have been "
                "served"
            )

    def _fail(self, msg: str) -> None:
        with self._mu:
            self.violations.append(msg)
        raise ViewGuardViolation(msg)

    def assert_clean(self) -> None:
        self.verify_outstanding("watch exit")
        if self.violations:
            raise ViewGuardViolation("; ".join(self.violations))

    @property
    def outstanding(self) -> int:
        with self._mu:
            return len(self._exports)


# the innermost active watch, so a test that DELIBERATELY mutates a
# buffer under a zero-copy view (the CRC-corruption fixtures) can
# release its export first instead of tripping the suite-wide sweep
_ACTIVE: list[ViewGuard] = []


def current() -> ViewGuard | None:
    return _ACTIVE[-1] if _ACTIVE else None


@contextlib.contextmanager
def watch() -> Iterator[ViewGuard]:
    """Instrument the view sources for the duration of the context:

      Needle.from_bytes(copy=False)  -> export payload views
      StagingArena.stage_fused/xla   -> reuse check + export
      DevicePipeline.slot            -> auto-release the slot arena's
                                        exports when the slot returns
      vacuum.commit                  -> verify outstanding views after
    """
    from seaweedfs_tpu.ops import rs_ingest, rs_resident
    from seaweedfs_tpu.storage import needle as needle_mod
    from seaweedfs_tpu.storage import vacuum as vacuum_mod

    g = ViewGuard()

    real_from_bytes = needle_mod.Needle.from_bytes.__func__
    real_stage_fused = rs_resident.StagingArena.stage_fused
    real_stage_xla = rs_resident.StagingArena.stage_xla
    real_slot = rs_resident.DevicePipeline.slot
    real_commit = vacuum_mod.commit
    real_dispatch = rs_resident._dispatch_call
    real_ing_stage = rs_ingest.IngestArena.stage
    real_ing_seal = rs_ingest.IngestArena.seal
    real_ing_reclaim = rs_ingest.IngestArena.reclaim
    real_ing_donatable = rs_ingest._donatable

    # nested watches stack their patches (a test's own watch() inside
    # the SWFS_VIEWGUARD session sweep): only the INNERMOST guard
    # registers, so a scoped test that deliberately scribbles under a
    # view (and verifies the violation itself) cannot leak an
    # already-poisoned export into the outer sweep's ledger
    def _mine() -> bool:
        return bool(_ACTIVE) and _ACTIVE[-1] is g

    def from_bytes(cls, buf, version=needle_mod.CURRENT_VERSION,
                   verify=True, copy=True):
        n = real_from_bytes(cls, buf, version, verify, copy)
        if (
            _mine() and not copy
            and isinstance(n.data, memoryview) and len(n.data)
        ):
            g.export(n.data, buf, f"needle {n.id:x} payload")
        return n

    def stage_fused(self, packed, pad):
        if _mine():
            g.check_reuse(self, "StagingArena.stage_fused reuses the arena")
        view = real_stage_fused(self, packed, pad)
        if _mine():
            g.export(view, self, f"arena fused meta [{len(packed)}+{pad}]")
        return view

    def stage_xla(self, offsets, rows, deltas, pad):
        if _mine():
            g.check_reuse(self, "StagingArena.stage_xla reuses the arena")
        view = real_stage_xla(self, offsets, rows, deltas, pad)
        if _mine():
            g.export(view, self, f"arena xla meta [{len(offsets)}+{pad}]")
        return view

    @contextlib.contextmanager
    def slot(self):
        with real_slot(self) as s:
            try:
                yield s
            finally:
                # the device call holding this slot has returned: its
                # arena exports are dead (verified on the way out)
                g.release_source(s.arena)

    def dispatch_call(kind, vec, *args, **kw):
        # donation boundary: the staged vec rides donate_argnums into
        # the kernel.  On a COPYING client (TPU: device_put copies) a
        # live arena export at this position is the designed fast path;
        # on a zero-copy PJRT client (CPU) it would hand the export's
        # actual memory to XLA — exactly the aliasing the arena gating
        # in reconstruct_intervals exists to prevent, enforced here so
        # a gating regression fails the test at the dispatch boundary.
        from seaweedfs_tpu.ops import rs_tpu

        if not rs_tpu.on_tpu():
            g.check_donation(vec, f"_dispatch_call({kind})")
        return real_dispatch(kind, vec, *args, **kw)

    def ing_stage(self, timeout_s=None):
        buf = real_ing_stage(self, timeout_s)
        if _mine():
            # the pool just handed this row out for overwrite: a still-
            # outstanding seal export over it means reclaim was skipped
            g.check_reuse(buf, "IngestArena.stage reuses a staging row")
        return buf

    def ing_seal(self, buf):
        out = real_ing_seal(self, buf)
        if _mine():
            g.export(
                out, out, f"ingest row [{self.k}, {self.block}]"
            )
        return out

    def ing_reclaim(self, buf):
        if _mine():
            # verifies the fingerprint: the encode leg must only READ
            # the sealed row between seal() and here
            g.release_source(buf)
        real_ing_reclaim(self, buf)

    def ing_donatable(rows, on_tpu):
        out = real_ing_donatable(rows, on_tpu)
        if _mine() and out is rows and not on_tpu:
            # the defensive-copy gate was skipped on a zero-copy client:
            # donating the live arena row hands its memory to XLA
            g.check_donation(rows, "rs_ingest._donatable")
        return out

    def commit(v, cpd, cpx, idx_snapshot, shadow_db=None):
        out = real_commit(v, cpd, cpx, idx_snapshot, shadow_db)
        # the .dat was just swapped: every outstanding zero-copy view
        # must still read its exported bytes (old preads are immutable
        # `bytes` over the old inode — this is what PROVES it)
        g.verify_outstanding(f"vacuum commit of volume {v.id}")
        return out

    needle_mod.Needle.from_bytes = classmethod(from_bytes)
    rs_resident.StagingArena.stage_fused = stage_fused
    rs_resident.StagingArena.stage_xla = stage_xla
    rs_resident.DevicePipeline.slot = slot
    vacuum_mod.commit = commit
    rs_resident._dispatch_call = dispatch_call
    rs_ingest.IngestArena.stage = ing_stage
    rs_ingest.IngestArena.seal = ing_seal
    rs_ingest.IngestArena.reclaim = ing_reclaim
    rs_ingest._donatable = ing_donatable
    _ACTIVE.append(g)
    try:
        yield g
    finally:
        _ACTIVE.remove(g)
        needle_mod.Needle.from_bytes = classmethod(real_from_bytes)
        rs_resident.StagingArena.stage_fused = real_stage_fused
        rs_resident.StagingArena.stage_xla = real_stage_xla
        rs_resident.DevicePipeline.slot = real_slot
        vacuum_mod.commit = real_commit
        rs_resident._dispatch_call = real_dispatch
        rs_ingest.IngestArena.stage = real_ing_stage
        rs_ingest.IngestArena.seal = real_ing_seal
        rs_ingest.IngestArena.reclaim = real_ing_reclaim
        rs_ingest._donatable = real_ing_donatable
