"""Repo-native developer tooling (not shipped with seaweedfs_tpu)."""
