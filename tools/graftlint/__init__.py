"""graftlint — repo-native static analysis for the EC serving stack.

The Go reference leans on `go vet` + the race detector; this port's
hazard surface (threaded DevicePipeline, cross-locking DeviceShardCache
eviction, async servers, hand-mutated pb2 descriptors, registry-driven
metrics/stages) gets the equivalent here: AST rules with repo knowledge,
a static lock-order graph, and a proto/registry drift check — all
runnable as `python -m tools.graftlint seaweedfs_tpu tests` and wired
into tier-1 (tests/test_lint_clean.py) and the __graft_entry__ dryrun.

The runtime complement (what static analysis can't see across callbacks)
is tests/lockwatch.py: it wraps the lock classes under pytest, records
ACTUAL acquisition orders, and fails on an observed cycle.
"""
from .engine import collect_files, main, run_paths
from .model import RULES, Finding, Rule, rule_table_markdown

__all__ = [
    "RULES",
    "Finding",
    "Rule",
    "collect_files",
    "main",
    "run_paths",
    "rule_table_markdown",
]
