"""graftlint driver: collect files, run rules, apply waivers, report.

Usage (the tier-1 entry point):

    python -m tools.graftlint seaweedfs_tpu tests

Exit 0 = tree clean.  Findings print as `path:line: GLnnn message`.

Waivers: a finding is suppressed when the flagged line — or the
contiguous comment block directly above it — carries a COMMENT reading
`# graftlint: allow(<rule-name>): reason`.  Waivers are for DELIBERATE
exceptions, not a mute button: GL113 fails the gate on any waiver that
no longer suppresses anything, so a waiver that outlives its violation
must be deleted with it.  Only real comment tokens count (a waiver
spelled inside a string literal is documentation, not a waiver).

Performance: per-file results are cached in `.graftlint_cache.json`
keyed by file content hash + a salt over the linter's own sources and
the metric/stage registry, so an unchanged file re-lints for the cost
of one hash; `--jobs N` fans uncached files over a process pool.  The
cross-file passes (lock order, proto drift, flag drift, unused-waiver
accounting) always run — they are cheap and their inputs span files.
"""
from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import sys
import tokenize
from dataclasses import dataclass, field

from . import flags as flags_mod
from . import flow, locks, proto, rules
from .model import STAGE_DRIFT, UNUSED_WAIVER, Finding, rule_by_id

# seeded-violation fixtures live here: the clean-tree run must skip them
# (they exist to FAIL), but linting the corpus dir explicitly works
_CORPUS_DIR = "lint_corpus"
_WAIVER_RE = re.compile(r"graftlint:\s*allow\(([\w-]+)\)")
_CACHE_NAME = ".graftlint_cache.json"
_CACHE_VERSION = 4


@dataclass
class FileUnit:
    path: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)
    # lineno -> waived rule name, from COMMENT tokens only
    waivers: dict[int, str] = field(default_factory=dict)


@dataclass
class FileResult:
    """Everything the cross-file passes need from one file — the unit
    of the fingerprint cache (must stay JSON-serializable)."""

    path: str
    findings: list[Finding] = field(default_factory=list)  # post-waiver
    waiver_lines: list[tuple[int, str]] = field(default_factory=list)
    used_waivers: list[int] = field(default_factory=list)
    flag_decls: list[tuple[str, int]] = field(default_factory=list)
    # GL117 inputs: this file's OWN `TRACE_STAGES = (...)` declaration
    # (line, names) if any, and the stage literals it records at
    # span()/record_span() call sites
    stage_decl: tuple[int, list[str]] | None = None
    stage_uses: list[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "findings": [
                [f.rule, f.path, f.line, f.message] for f in self.findings
            ],
            "waivers": list(self.waiver_lines),
            "used": list(self.used_waivers),
            "flags": list(self.flag_decls),
            "stage_decl": (
                [self.stage_decl[0], list(self.stage_decl[1])]
                if self.stage_decl is not None else None
            ),
            "stage_uses": list(self.stage_uses),
        }

    @classmethod
    def from_json(cls, path: str, d: dict) -> "FileResult":
        sd = d.get("stage_decl")
        return cls(
            path=path,
            findings=[Finding(*row) for row in d.get("findings", ())],
            waiver_lines=[tuple(w) for w in d.get("waivers", ())],
            used_waivers=list(d.get("used", ())),
            flag_decls=[tuple(w) for w in d.get("flags", ())],
            stage_decl=(int(sd[0]), list(sd[1])) if sd else None,
            stage_uses=list(d.get("stage_uses", ())),
        )


def collect_files(paths: list[str], include_corpus: bool = False) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames
                if d != "__pycache__"
                and (include_corpus or d != _CORPUS_DIR)
            ]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                if fn.endswith("_pb2.py"):
                    # generated descriptor-blob modules: no hand-written
                    # logic to lint, and their megaline literals are not
                    # series/stage names
                    continue
                out.append(os.path.join(root, fn))
    return sorted(set(out))


def comment_waivers(src: str) -> dict[int, str]:
    """lineno -> rule name for every `# graftlint: allow(<rule>)` that
    is a real COMMENT token.  Waiver text inside string literals is
    deliberately ignored (GL113 would otherwise flag the lint's own
    docstrings as stale waivers)."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                m = _WAIVER_RE.search(tok.string)
                if m:
                    out[tok.start[0]] = m.group(1)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # the parse pass reports the syntax error as GL000
    return out


def parse_unit(path: str, src: str) -> tuple[FileUnit | None, Finding | None]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return None, Finding(
            "GL000", path, e.lineno or 0, f"syntax error: {e.msg}"
        )
    return FileUnit(
        path, tree, src.splitlines(), comment_waivers(src)
    ), None


def _registry_context(
    file_paths: list[str],
) -> tuple[set[str], set[str]]:
    """Declared series bases + stage names, parsed from the registry
    modules inside the linted set when present, else from the repo's
    own stats package (so linting a single file still has the registry
    to check against)."""
    series: set[str] = set()
    stages: set[str] = set()
    reg_paths = [p for p in file_paths if _is_registry_module(p)]
    if not reg_paths:
        repo_root = _repo_root()
        reg_paths = [
            os.path.join(repo_root, rel)
            for rel in ("seaweedfs_tpu/stats/metrics.py",
                        "seaweedfs_tpu/stats/cluster.py")
        ]
    for p in reg_paths:
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=p)
            except SyntaxError:
                continue
        series |= rules.declared_series(tree)
        stages |= rules.declared_stages(tree)
    return series, stages


def _is_registry_module(path: str) -> bool:
    p = path.replace("\\", "/")
    return p.endswith(("stats/metrics.py", "stats/cluster.py"))


_RPC_RE = re.compile(r"\brpc\s+(\w+)")


def _rpc_context() -> set[str]:
    """Proto rpc method names from the repo's own pb/*.proto — the
    GL114 universe of cross-node call attributes.  Read from the repo
    (like the registry fallback): linting a loose file set must still
    know what an RPC is."""
    names: set[str] = set()
    pb_dir = os.path.join(_repo_root(), "seaweedfs_tpu", "pb")
    if not os.path.isdir(pb_dir):
        return names
    for fn in sorted(os.listdir(pb_dir)):
        if not fn.endswith(".proto"):
            continue
        try:
            with open(os.path.join(pb_dir, fn), encoding="utf-8") as f:
                names |= set(_RPC_RE.findall(f.read()))
        except OSError:
            continue
    return names


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _waiver_line_for(unit: FileUnit, finding: Finding) -> int | None:
    """Line of the waiver covering `finding`, else None.  The flagged
    line itself or the contiguous comment block directly above it."""
    rule_name = rule_by_id(finding.rule).name if finding.rule != "GL000" else ""

    def hit(lineno: int) -> bool:
        got = unit.waivers.get(lineno)
        return got is not None and got in (rule_name, finding.rule, "all")

    if not (1 <= finding.line <= len(unit.lines)):
        return None
    if hit(finding.line):
        return finding.line
    lineno = finding.line - 1
    while lineno >= 1 and unit.lines[lineno - 1].lstrip().startswith("#"):
        if hit(lineno):
            return lineno
        lineno -= 1
    return None


# ------------------------------------------------------- per-file stage


def lint_one_file(
    path: str,
    series: tuple[str, ...],
    stages: tuple[str, ...],
    rpcs: tuple[str, ...] = (),
) -> FileResult:
    """Run every per-file rule over one file and apply its waivers.
    Pure function of (file content, registry context) — the unit of
    both the fingerprint cache and the --jobs process pool."""
    res = FileResult(path)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    unit, err = parse_unit(path, src)
    if err is not None:
        res.findings.append(err)
        return res
    assert unit is not None
    res.waiver_lines = sorted(unit.waivers.items())
    res.flag_decls = flags_mod.flag_decls(unit.tree, path)
    res.stage_decl = rules.stage_decl_site(unit.tree)
    res.stage_uses = sorted(rules.stage_use_literals(unit.tree))

    raw: list[Finding] = []
    raw += rules.check_async_blocking(unit.tree, path)
    raw += rules.check_device_sync(unit.tree, path)
    raw += rules.check_jit_static(unit.tree, path)
    raw += rules.check_metric_registry(
        unit.tree, path, set(series), _is_registry_module(path)
    )
    raw += rules.check_stage_registry(unit.tree, path, set(stages))
    raw += rules.check_silent_swallow(unit.tree, path)
    raw += rules.check_unbounded_rpc(unit.tree, path, set(rpcs))
    raw += rules.check_unsharded_device_put(unit.tree, path)
    raw += rules.check_process_local_device(unit.tree, path)
    raw += rules.check_untagged_device_dispatch(unit.tree, path)
    raw += flow.check_view_escape(unit.tree, path)
    raw += flow.check_use_after_donate(unit.tree, path)
    raw += flow.check_task_leak(unit.tree, path)

    used: set[int] = set()
    for f in raw:
        w = _waiver_line_for(unit, f)
        if w is None:
            res.findings.append(f)
        else:
            used.add(w)
    res.used_waivers = sorted(used)
    return res


# ------------------------------------------------------------ cache


def _file_fingerprint(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def _tool_salt(
    series: tuple[str, ...],
    stages: tuple[str, ...],
    rpcs: tuple[str, ...] = (),
) -> str:
    """Changes whenever the linter itself (any tools/graftlint source)
    or the registry/rpc context changes — any of them invalidates every
    cached per-file result."""
    h = hashlib.sha256()
    h.update(f"v{_CACHE_VERSION}py{sys.version_info[:2]}".encode())
    tool_dir = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(tool_dir)):
        if fn.endswith(".py"):
            with open(os.path.join(tool_dir, fn), "rb") as f:
                h.update(f.read())
    for name in series + ("|",) + stages + ("|",) + rpcs:
        h.update(name.encode())
    return h.hexdigest()


class _Cache:
    def __init__(self, path: str, salt: str, enabled: bool):
        self.path = path
        self.salt = salt
        self.enabled = enabled
        self._files: dict[str, dict] = {}
        self._dirty = False
        if not enabled:
            return
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if data.get("salt") == salt:
                self._files = data.get("files", {})
        except (OSError, ValueError):
            self._files = {}

    def get(self, path: str, fp: str) -> FileResult | None:
        if not self.enabled:
            return None
        entry = self._files.get(path)
        if entry and entry.get("fp") == fp:
            try:
                return FileResult.from_json(path, entry["res"])
            except (KeyError, TypeError):
                return None
        return None

    def put(self, path: str, fp: str, res: FileResult) -> None:
        if not self.enabled:
            return
        self._files[path] = {"fp": fp, "res": res.to_json()}
        self._dirty = True

    def save(self) -> None:
        if not (self.enabled and self._dirty):
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"salt": self.salt, "files": self._files}, f)
            os.replace(tmp, self.path)
        except OSError:
            # cache is an accelerator, never a correctness input: a
            # read-only checkout just re-lints every file
            try:
                os.remove(tmp)
            except OSError:
                pass


# ------------------------------------------------------------- driver


def run_paths(
    paths: list[str],
    proto_pb2_package: str = "seaweedfs_tpu.pb",
    include_corpus: bool = False,
    jobs: int = 1,
    use_cache: bool = True,
) -> list[Finding]:
    findings: list[Finding] = []
    for p in paths:
        # a missing target must FAIL, not lint zero files as "clean":
        # a typo in the tier-1/dryrun invocation would otherwise
        # permanently greenlight an unlinted tree
        if not os.path.exists(p):
            findings.append(Finding(
                "GL000", p, 0,
                "path does not exist — fix the lint invocation",
            ))
    file_paths = collect_files(paths, include_corpus=include_corpus)
    series_set, stages_set = _registry_context(file_paths)
    series = tuple(sorted(series_set))
    stages = tuple(sorted(stages_set))
    rpcs = tuple(sorted(_rpc_context()))

    cache = _Cache(
        os.environ.get("SWFS_LINT_CACHE")
        or os.path.join(_repo_root(), _CACHE_NAME),
        _tool_salt(series, stages, rpcs),
        enabled=use_cache,
    )

    results: dict[str, FileResult] = {}
    todo: list[tuple[str, str]] = []  # (path, fingerprint)
    for path in file_paths:
        try:
            fp = _file_fingerprint(path)
        except OSError as e:
            findings.append(Finding("GL000", path, 0, f"unreadable: {e}"))
            continue
        hit = cache.get(path, fp)
        if hit is not None:
            results[path] = hit
        else:
            todo.append((path, fp))

    if jobs > 1 and len(todo) > 1:
        import concurrent.futures

        with concurrent.futures.ProcessPoolExecutor(
            max_workers=min(jobs, len(todo))
        ) as pool:
            for (path, fp), res in zip(
                todo,
                pool.map(
                    lint_one_file,
                    [p for p, _ in todo],
                    [series] * len(todo),
                    [stages] * len(todo),
                    [rpcs] * len(todo),
                ),
            ):
                results[path] = res
                cache.put(path, fp, res)
    else:
        for path, fp in todo:
            res = lint_one_file(path, series, stages, rpcs)
            results[path] = res
            cache.put(path, fp, res)

    for path in file_paths:
        if path in results:
            findings.extend(results[path].findings)

    # waiver usage across EVERY pass feeds GL113 at the end
    used_by_path: dict[str, set[int]] = {
        p: set(r.used_waivers) for p, r in results.items()
    }

    # cross-file: the static lock-order graph over the serving stack.
    # Lock-scope files are re-parsed here even when their per-file
    # results were cached — the graph's inputs span files, so its
    # findings can never be cached per-file.  Findings anchor at a
    # lock's declaration site, so the normal waiver channel applies
    # there (conservative call resolution can err — a reasoned
    # `# graftlint: allow(lock-order)` must be able to say so)
    lock_units: dict[str, FileUnit] = {}
    for path in file_paths:
        if not locks.in_lock_scope(path):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                unit, _err = parse_unit(path, f.read())
        except OSError:
            continue
        if unit is not None:
            lock_units[path] = unit
    for f in locks.check_lock_order(
        {p: u.tree for p, u in lock_units.items()}
    ):
        u = lock_units.get(f.path)
        w = _waiver_line_for(u, f) if u is not None else None
        if w is None:
            findings.append(f)
        else:
            used_by_path.setdefault(f.path, set()).add(w)

    # proto drift: any pb/ directory with .proto files inside the linted
    # paths (the real tree's seaweedfs_tpu/pb)
    seen_dirs: set[str] = set()
    for p in paths:
        base = p if os.path.isdir(p) else os.path.dirname(p)
        for root, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            if any(f.endswith(".proto") for f in filenames):
                seen_dirs.add(root)
    for d in sorted(seen_dirs):
        if _CORPUS_DIR in d.replace("\\", "/") and not include_corpus:
            continue
        findings.extend(proto.check_proto_dir(d, proto_pb2_package))

    # GL112 flag drift: declarations from every linted file vs README
    # and the config modules.  The README/config reverse directions
    # only run on a full-tree lint (command/ modules present).
    decls = [
        (flag, p, line)
        for p, r in results.items()
        for flag, line in r.flag_decls
    ]
    full_tree = any(
        "seaweedfs_tpu/command/" in p.replace("\\", "/") for p in results
    )
    # memoized waiver-unit lookup keyed by ABSOLUTE path: flag-drift
    # findings in config modules carry repo_root-joined paths while the
    # linted set is keyed as-invoked (often relative) — without the
    # normalization a config-module waiver could never suppress (and
    # would then be double-reported as GL113 unused)
    waiver_units: dict[str, FileUnit | None] = {
        os.path.abspath(p): u for p, u in lock_units.items()
    }

    def _unit_for(path: str) -> FileUnit | None:
        ap = os.path.abspath(path)
        if ap not in waiver_units:
            unit = None
            if path.endswith(".py"):
                try:
                    with open(ap, encoding="utf-8") as fh:
                        unit, _err = parse_unit(path, fh.read())
                except OSError:
                    unit = None
            waiver_units[ap] = unit
        return waiver_units[ap]

    for f in flags_mod.check_flag_drift(decls, _repo_root(), full_tree):
        u = _unit_for(f.path)
        w = _waiver_line_for(u, f) if u is not None else None
        if w is None:
            findings.append(f)
        else:
            # key by every alias of the path present in `results` so the
            # GL113 pass (keyed as-invoked) sees the use
            ap = os.path.abspath(f.path)
            for p in results:
                if os.path.abspath(p) == ap:
                    used_by_path.setdefault(p, set()).add(w)
                    break
            else:
                used_by_path.setdefault(f.path, set()).add(w)

    # GL117 stage drift: every stage a linted `TRACE_STAGES = (...)`
    # tuple declares must be recorded — as a span()/record_span()
    # literal — SOMEWHERE in the linted set.  Anchored on the declaring
    # file/line (only modules that themselves declare the tuple judge:
    # a loose file set without the registry judges nothing), so the
    # normal waiver channel applies at the declaration.
    all_stage_uses: set[str] = set()
    for r in results.values():
        all_stage_uses.update(r.stage_uses)
    for path in sorted(results):
        decl = results[path].stage_decl
        if decl is None:
            continue
        decl_line, names = decl
        for name in names:
            if name in all_stage_uses:
                continue
            f = Finding(
                STAGE_DRIFT.rule_id, path, decl_line,
                f"trace stage {name!r} is declared in TRACE_STAGES but "
                "no span()/record_span() call site in the linted tree "
                "records it — delete the dead stage (and its README "
                "row) or instrument the code path it was meant for",
            )
            u = _unit_for(f.path)
            w = _waiver_line_for(u, f) if u is not None else None
            if w is None:
                findings.append(f)
            else:
                used_by_path.setdefault(f.path, set()).add(w)

    # GL113 unused waivers: every comment waiver that suppressed nothing
    # in ANY pass above.  Computed last so cross-file suppressions count
    # as use; not itself waivable (a waiver for the unused-waiver rule
    # would be unused by construction).
    for path in sorted(results):
        used = used_by_path.get(path, set())
        for line, rule_name in results[path].waiver_lines:
            if line not in used:
                findings.append(Finding(
                    UNUSED_WAIVER.rule_id, path, line,
                    f"waiver allow({rule_name}) suppresses nothing — "
                    "the violation it covered is gone; delete the "
                    "waiver (or fix the rule name if it drifted)",
                ))

    cache.save()
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def main(argv: list[str]) -> int:
    import argparse

    from .model import rule_table_markdown
    from .mypy_gate import run_mypy

    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="repo-native static analysis for the EC serving stack",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument(
        "--doc", action="store_true",
        help="print the README rule table generated from the registry",
    )
    ap.add_argument(
        "--mypy", action="store_true",
        help="also run the strict-typing gate (mypy.ini adoption list; "
        "skipped when mypy is not installed)",
    )
    ap.add_argument(
        "--proto-pb2-package", default="seaweedfs_tpu.pb",
        help="package the *_pb2 modules live in (proto-drift rule)",
    )
    ap.add_argument(
        "--include-corpus", action="store_true",
        help="lint tests/lint_corpus too (it is SEEDED with violations)",
    )
    ap.add_argument(
        "--jobs", type=int,
        default=int(os.environ.get("SWFS_LINT_JOBS", "1") or "1"),
        help="process-pool width for uncached files (default: "
        "$SWFS_LINT_JOBS or 1)",
    )
    ap.add_argument(
        "--no-cache", action="store_true",
        help="ignore and do not write .graftlint_cache.json",
    )
    args = ap.parse_args(argv)

    if args.doc:
        print(rule_table_markdown())
        return 0

    rc = 0
    if args.paths:
        findings = run_paths(
            args.paths,
            proto_pb2_package=args.proto_pb2_package,
            include_corpus=args.include_corpus,
            jobs=max(1, args.jobs),
            use_cache=not args.no_cache,
        )
        for f in findings:
            print(f.render())
        if findings:
            print(f"graftlint: {len(findings)} finding(s)")
            rc = 1
        else:
            print(f"graftlint: clean ({', '.join(args.paths)})")
    if args.mypy:
        mypy_rc, out = run_mypy(_repo_root())
        print(out)
        rc = rc or mypy_rc
    if not args.paths and not args.mypy:
        ap.print_usage()
        return 2
    return rc
