"""graftlint driver: collect files, run rules, apply waivers, report.

Usage (the tier-1 entry point):

    python -m tools.graftlint seaweedfs_tpu tests

Exit 0 = tree clean.  Findings print as `path:line: GLnnn message`.

Waivers: a finding is suppressed when the flagged line or the line
directly above carries `# graftlint: allow(<rule-name>)` — a reason
after the colon is expected and reviewed like any comment.  Waivers are
for DELIBERATE exceptions (an explicit tiny D2H the code wants), not a
mute button; every waiver names its rule so a grep lists them all.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from . import locks, proto, rules
from .model import Finding, rule_by_id

# seeded-violation fixtures live here: the clean-tree run must skip them
# (they exist to FAIL), but linting the corpus dir explicitly works
_CORPUS_DIR = "lint_corpus"
_WAIVER_RE = re.compile(r"graftlint:\s*allow\(([\w-]+)\)")


@dataclass
class FileUnit:
    path: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)


def collect_files(paths: list[str], include_corpus: bool = False) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames
                if d != "__pycache__"
                and (include_corpus or d != _CORPUS_DIR)
            ]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                if fn.endswith("_pb2.py"):
                    # generated descriptor-blob modules: no hand-written
                    # logic to lint, and their megaline literals are not
                    # series/stage names
                    continue
                out.append(os.path.join(root, fn))
    return sorted(set(out))


def parse_files(file_paths: list[str]) -> tuple[list[FileUnit], list[Finding]]:
    units: list[FileUnit] = []
    findings: list[Finding] = []
    for path in file_paths:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                "GL000", path, e.lineno or 0, f"syntax error: {e.msg}"
            ))
            continue
        units.append(FileUnit(path, tree, src.splitlines()))
    return units, findings


def _registry_context(units: list[FileUnit]) -> tuple[set[str], set[str]]:
    """Declared series bases + stage names.  Parsed from the linted
    tree when stats/ is part of it, else from the repo's own stats
    package relative to this file (so linting a single file still has
    the registry to check against)."""
    series: set[str] = set()
    stages: set[str] = set()
    reg_units = [u for u in units if _is_registry_module(u.path)]
    if not reg_units:
        repo_root = _repo_root()
        for rel in ("seaweedfs_tpu/stats/metrics.py",
                    "seaweedfs_tpu/stats/cluster.py"):
            p = os.path.join(repo_root, rel)
            if os.path.exists(p):
                with open(p, encoding="utf-8") as f:
                    reg_units.append(
                        FileUnit(p, ast.parse(f.read(), filename=p))
                    )
    for u in reg_units:
        series |= rules.declared_series(u.tree)
        stages |= rules.declared_stages(u.tree)
    return series, stages


def _is_registry_module(path: str) -> bool:
    p = path.replace("\\", "/")
    return p.endswith(("stats/metrics.py", "stats/cluster.py"))


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _waived(unit: FileUnit, finding: Finding) -> bool:
    """True when the flagged line — or the contiguous comment block
    directly above it — carries `# graftlint: allow(<rule>)`."""
    rule_name = rule_by_id(finding.rule).name if finding.rule != "GL000" else ""

    def hit(lineno: int) -> bool:
        m = _WAIVER_RE.search(unit.lines[lineno - 1])
        return bool(m) and m.group(1) in (rule_name, finding.rule, "all")

    if not (1 <= finding.line <= len(unit.lines)):
        return False
    if hit(finding.line):
        return True
    lineno = finding.line - 1
    while lineno >= 1 and unit.lines[lineno - 1].lstrip().startswith("#"):
        if hit(lineno):
            return True
        lineno -= 1
    return False


def run_paths(
    paths: list[str],
    proto_pb2_package: str = "seaweedfs_tpu.pb",
    include_corpus: bool = False,
) -> list[Finding]:
    findings: list[Finding] = []
    for p in paths:
        # a missing target must FAIL, not lint zero files as "clean":
        # a typo in the tier-1/dryrun invocation would otherwise
        # permanently greenlight an unlinted tree
        if not os.path.exists(p):
            findings.append(Finding(
                "GL000", p, 0,
                "path does not exist — fix the lint invocation",
            ))
    file_paths = collect_files(paths, include_corpus=include_corpus)
    units, parse_findings = parse_files(file_paths)
    findings.extend(parse_findings)
    series, stages = _registry_context(units)

    for u in units:
        per_file: list[Finding] = []
        per_file += rules.check_async_blocking(u.tree, u.path)
        per_file += rules.check_device_sync(u.tree, u.path)
        per_file += rules.check_jit_static(u.tree, u.path)
        per_file += rules.check_metric_registry(
            u.tree, u.path, series, _is_registry_module(u.path)
        )
        per_file += rules.check_stage_registry(u.tree, u.path, stages)
        per_file += rules.check_silent_swallow(u.tree, u.path)
        findings.extend(f for f in per_file if not _waived(u, f))

    # cross-file: the static lock-order graph over the serving stack.
    # Findings anchor at a lock's declaration site, so the normal waiver
    # channel applies there (conservative call resolution can err — a
    # reasoned `# graftlint: allow(lock-order)` must be able to say so)
    units_by_path = {u.path: u for u in units}
    for f in locks.check_lock_order({u.path: u.tree for u in units}):
        u = units_by_path.get(f.path)
        if u is None or not _waived(u, f):
            findings.append(f)

    # proto drift: any pb/ directory with .proto files inside the linted
    # paths (the real tree's seaweedfs_tpu/pb)
    seen_dirs: set[str] = set()
    for p in paths:
        base = p if os.path.isdir(p) else os.path.dirname(p)
        for root, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            if any(f.endswith(".proto") for f in filenames):
                seen_dirs.add(root)
    for d in sorted(seen_dirs):
        if _CORPUS_DIR in d.replace("\\", "/") and not include_corpus:
            continue
        findings.extend(proto.check_proto_dir(d, proto_pb2_package))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def main(argv: list[str]) -> int:
    import argparse

    from .model import rule_table_markdown
    from .mypy_gate import run_mypy

    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="repo-native static analysis for the EC serving stack",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument(
        "--doc", action="store_true",
        help="print the README rule table generated from the registry",
    )
    ap.add_argument(
        "--mypy", action="store_true",
        help="also run the strict-typing gate (mypy.ini adoption list; "
        "skipped when mypy is not installed)",
    )
    ap.add_argument(
        "--proto-pb2-package", default="seaweedfs_tpu.pb",
        help="package the *_pb2 modules live in (proto-drift rule)",
    )
    ap.add_argument(
        "--include-corpus", action="store_true",
        help="lint tests/lint_corpus too (it is SEEDED with violations)",
    )
    args = ap.parse_args(argv)

    if args.doc:
        print(rule_table_markdown())
        return 0

    rc = 0
    if args.paths:
        findings = run_paths(
            args.paths,
            proto_pb2_package=args.proto_pb2_package,
            include_corpus=args.include_corpus,
        )
        for f in findings:
            print(f.render())
        if findings:
            print(f"graftlint: {len(findings)} finding(s)")
            rc = 1
        else:
            print(f"graftlint: clean ({', '.join(args.paths)})")
    if args.mypy:
        mypy_rc, out = run_mypy(_repo_root())
        print(out)
        rc = rc or mypy_rc
    if not args.paths and not args.mypy:
        ap.print_usage()
        return 2
    return rc
