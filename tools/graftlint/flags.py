"""GL112 flag-drift: the `-ec.*`/`-obs.*` CLI surface is a three-way
contract — the `add_argument` declaration in command/, the config
dataclass the value lands in (ServingConfig / BulkConfig / ObsConfig),
and the README flag table an operator reads.  This rule pins all three
to each other, both directions:

  1. every declared flag must have a README flag-table row;
  2. every declared flag in a config-owned namespace must be NAMED in
     its config module's source (comments count — the dataclass field
     comments are where flags are documented per-knob);
  3. every README row must correspond to a declared flag (stale docs);
  4. every config-source flag mention must correspond to a declared
     flag (stale comments).

Directions 3 and 4 only run when the linted set actually contains the
command/ modules (a full-tree run): linting a loose file set must not
report the whole README as drifted.

Wildcard doc references like `-ec.qos.*` are skipped — the rule wants
every real knob named somewhere exact, and the namespace prose can stay.
"""
from __future__ import annotations

import os
import re
from typing import Iterable, Iterator

from .model import FLAG_DRIFT, Finding

_FLAG_RE = re.compile(
    r"-(?:ec|obs)\.[A-Za-z][A-Za-z0-9]*(?:\.[A-Za-z][A-Za-z0-9]*)*"
)
# README table row: `| `-ec.foo` | ...`
_README_ROW_RE = re.compile(r"^\|\s*`(-(?:ec|obs)\.[^`]+)`")

# namespace -> config module (repo-relative) that must name each flag.
# Order matters: config_owner() returns the FIRST matching prefix, so
# sub-namespaces with their own config module (-obs.slo.*,
# -obs.incident.*) must precede their parent's catch-all entry.
CONFIG_OWNERS: tuple[tuple[str, str], ...] = (
    ("-ec.serving.", "seaweedfs_tpu/serving/config.py"),
    ("-ec.mesh.", "seaweedfs_tpu/serving/config.py"),
    ("-ec.qos.", "seaweedfs_tpu/serving/config.py"),
    ("-ec.tier.", "seaweedfs_tpu/serving/config.py"),
    ("-ec.ingest.", "seaweedfs_tpu/ingest/config.py"),
    ("-ec.repair.", "seaweedfs_tpu/repair/config.py"),
    ("-ec.rpc.", "seaweedfs_tpu/utils/faultpolicy.py"),
    ("-ec.bulk.", "seaweedfs_tpu/storage/ec/bulk.py"),
    ("-obs.slo.", "seaweedfs_tpu/obs/slo.py"),
    ("-obs.incident.", "seaweedfs_tpu/obs/incident.py"),
    ("-obs.", "seaweedfs_tpu/obs/config.py"),
)


def config_owner(flag: str) -> str | None:
    for prefix, path in CONFIG_OWNERS:
        if flag.startswith(prefix):
            return path
    return None


def flag_decls(tree, path: str) -> list[tuple[str, int]]:
    """(flag, line) for every add_argument("-ec..."/"-obs...") literal
    in one parsed file."""
    import ast

    from .rules import _str_const, dotted

    out: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        if not name.endswith("add_argument") or not node.args:
            continue
        lit = _str_const(node.args[0])
        if lit and (lit.startswith("-ec.") or lit.startswith("-obs.")):
            out.append((lit, node.lineno))
    return out


def _mentions(source: str) -> list[tuple[str, int]]:
    """Exact flag literals mentioned anywhere in a source text (comments
    and docstrings included), wildcard references skipped."""
    out: list[tuple[str, int]] = []
    for lineno, line in enumerate(source.splitlines(), start=1):
        for m in _FLAG_RE.finditer(line):
            tail = line[m.end():]
            if tail.startswith("*") or tail.startswith(".*"):
                continue  # `-ec.qos.*DeadlineMs`-style namespace prose
            out.append((m.group(0), lineno))
    return out


def check_flag_drift(
    decls: Iterable[tuple[str, str, int]],  # (flag, path, line)
    repo_root: str,
    full_tree: bool,
) -> Iterator[Finding]:
    decls = list(decls)
    declared = {flag for flag, _, _ in decls}

    readme_path = os.path.join(repo_root, "README.md")
    readme_rows: list[tuple[str, int]] = []
    if os.path.exists(readme_path):
        with open(readme_path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                m = _README_ROW_RE.match(line)
                if m:
                    readme_rows.append((m.group(1), lineno))
    readme_flags = {flag for flag, _ in readme_rows}

    config_texts: dict[str, list[tuple[str, int]]] = {}
    for _, rel in CONFIG_OWNERS:
        if rel in config_texts:
            continue
        p = os.path.join(repo_root, rel)
        if os.path.exists(p):
            with open(p, encoding="utf-8") as f:
                config_texts[rel] = _mentions(f.read())
        else:
            config_texts[rel] = []

    # 1 + 2: declaration-side checks
    for flag, path, line in decls:
        if flag not in readme_flags:
            yield Finding(
                FLAG_DRIFT.rule_id, path, line,
                f"flag {flag!r} has no README flag-table row — an "
                "operator cannot discover it; add the row (and keep the "
                "default/meaning columns honest)",
            )
        owner = config_owner(flag)
        if owner is not None:
            mentioned = {f for f, _ in config_texts.get(owner, ())}
            if flag not in mentioned:
                yield Finding(
                    FLAG_DRIFT.rule_id, path, line,
                    f"flag {flag!r} is not named in its config module "
                    f"{owner} — the dataclass field it lands in must "
                    "document which flag feeds it",
                )

    if not full_tree:
        return

    # 3: README rows with no declaration
    for flag, lineno in readme_rows:
        if flag not in declared:
            yield Finding(
                FLAG_DRIFT.rule_id, readme_path, lineno,
                f"README flag-table row {flag!r} matches no "
                "add_argument declaration — stale doc row",
            )
    # 4: config mentions with no declaration
    for rel, mentions in config_texts.items():
        for flag, lineno in mentions:
            if flag not in declared:
                yield Finding(
                    FLAG_DRIFT.rule_id, os.path.join(repo_root, rel), lineno,
                    f"config comment names {flag!r} but no add_argument "
                    "declares it — stale config doc",
                )
