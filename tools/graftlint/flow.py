"""Dataflow-aware per-file rules (graftflow): GL109/GL110/GL111.

Where rules.py checks statements in isolation, these follow values —
which names hold views of reusable buffers, which arrays a jitted call
donated, which spawned tasks anybody still holds.  All of it stays
stdlib-ast and deliberately intraprocedural: a lint that guesses across
call boundaries starts lying, and the runtime sanitizers
(tests/viewguard.py, tests/lockwatch.py) own the cross-function half.

The hazard classes are the ones r11/r13 created:

  * GL109 — zero-copy made needle payloads memoryviews over their source
    buffers; a view over a REUSABLE buffer (bytearray, np.empty staging,
    an arena attribute) that escapes into a field/container/scheduled
    closure outlives the deriving frame, and the next reuse scribbles
    over bytes the holder still reads.  Views over immutable `bytes`
    (pread results) are safe and not tracked.
  * GL110 — donate_argnums hands the buffer to XLA; touching the name
    again afterwards (without rebinding it to the call's result) reads
    memory the kernel may have aliased as output.
  * GL111 — a dropped create_task/ensure_future handle is a task the GC
    can cancel mid-flight and whose exception nobody ever observes; an
    `except CancelledError` that neither re-raises nor follows this
    function's own `.cancel()` converts shutdown into a silent hang.
"""
from __future__ import annotations

import ast
from typing import Iterator

from .model import TASK_LEAK, USE_AFTER_DONATE, VIEW_ESCAPE, Finding
from .rules import dotted


def _walk_same_function(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested function /
    lambda scopes (same contract as rules._walk_same_function)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _functions(tree: ast.Module) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ------------------------------------------------------- GL109 view-escape

# allocators of REUSABLE/MUTABLE buffers: a view over one of these is
# only valid while the allocation is neither reused nor freed.  Views
# over immutable `bytes` (pread returns) are deliberately not tracked —
# the refcount keeps those alive and nothing can mutate them.
_MUTABLE_ALLOC = {
    "bytearray",
    "np.empty", "np.zeros", "np.ones", "np.empty_like", "np.zeros_like",
    "numpy.empty", "numpy.zeros", "numpy.ones",
    "np.frombuffer", "numpy.frombuffer",
    "mmap.mmap",
}
# methods that produce another view of the same memory when called on a
# tracked view/buffer name
_VIEW_METHODS = {"cast", "toreadonly", "reshape", "view", "ravel"}
# scheduling sinks: a closure handed to one of these outlives the frame
_SCHEDULERS = (
    "create_task", "ensure_future", "call_soon", "call_later",
    "call_soon_threadsafe", "add_done_callback", "submit", "run_coroutine_threadsafe",
)
_CONTAINER_ADD = {"append", "add", "appendleft", "extend", "insert"}


def _mutable_buffer_attrs(cls: ast.ClassDef) -> set[str]:
    """Attribute names this class binds to reusable buffers
    (`self.X = np.empty(...)` anywhere in its methods)."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if (
            isinstance(node.value, ast.Call)
            and dotted(node.value.func) in _MUTABLE_ALLOC
        ):
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.add(t.attr)
    return out


class _ViewTracker:
    """Per-function name state: which locals hold a reusable buffer,
    which hold a view derived from one."""

    def __init__(self, buffer_attrs: set[str]):
        self.buffers: set[str] = set()
        self.views: set[str] = set()
        self.buffer_attrs = buffer_attrs  # self.<attr> reusable buffers

    def _is_tracked_source(self, node: ast.AST) -> bool:
        """True when `node` evaluates to a tracked buffer or view."""
        name = dotted(node)
        if name is None:
            return False
        if name in self.buffers or name in self.views:
            return True
        return name.startswith("self.") and name[5:] in self.buffer_attrs

    def classify(self, value: ast.AST) -> str | None:
        """'buffer' | 'view' | None for an expression.  Recursive so
        chained derivations (`memoryview(scratch)[16:128]`) resolve."""
        if self._is_tracked_source(value):
            name = dotted(value) or ""
            if name in self.views:
                return "view"
            return "buffer"
        if isinstance(value, ast.Call):
            fname = dotted(value.func)
            if fname in _MUTABLE_ALLOC:
                return "buffer"
            if fname == "memoryview" and value.args and (
                self.classify(value.args[0]) is not None
            ):
                return "view"
            if (
                isinstance(value.func, ast.Attribute)
                and value.func.attr in _VIEW_METHODS
                and self.classify(value.func.value) is not None
            ):
                return "view"
        if isinstance(value, ast.Subscript) and (
            self.classify(value.value) is not None
        ):
            # a subscript only yields a VIEW when it slices (scalar
            # indexing of a bytearray yields an int, of an ndarray a
            # scalar/row copy-or-view — only slices are unambiguous)
            if _has_slice(value.slice):
                return "view"
        return None

    def is_view_expr(self, node: ast.AST) -> bool:
        """True for an expression that IS a tracked view (a view-holding
        name, or an inline derivation from a tracked source)."""
        name = dotted(node)
        if name is not None and name in self.views:
            return True
        return self.classify(node) == "view"


def _has_slice(node: ast.AST) -> bool:
    if isinstance(node, ast.Slice):
        return True
    if isinstance(node, ast.Tuple):
        return any(isinstance(e, ast.Slice) for e in node.elts)
    return False


def check_view_escape(tree: ast.Module, path: str) -> Iterator[Finding]:
    # class pass: reusable buffers held as attributes (arena pattern)
    attrs_by_class: dict[ast.AST, set[str]] = {}
    class_of_fn: dict[ast.AST, ast.ClassDef | None] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            attrs_by_class[node] = _mutable_buffer_attrs(node)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_of_fn[sub] = node

    for fn in _functions(tree):
        cls = class_of_fn.get(fn)
        tracker = _ViewTracker(attrs_by_class.get(cls, set()) if cls else set())
        nodes = sorted(
            _walk_same_function(fn),
            key=lambda n: (getattr(n, "lineno", 0), getattr(n, "col_offset", 0)),
        )
        # pass 1: bind names (source order so derivations chain)
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                kind = tracker.classify(node.value)
                name = node.targets[0].id
                tracker.buffers.discard(name)
                tracker.views.discard(name)
                if kind == "buffer":
                    tracker.buffers.add(name)
                elif kind == "view":
                    tracker.views.add(name)
        # pass 2: escapes
        for node in nodes:
            yield from _escapes_in(node, tracker, path, fn)
        # pass 3: closures over tracked views handed to schedulers or
        # stored on attributes
        yield from _closure_escapes(fn, tracker, path)


def _escapes_in(node, tracker: "_ViewTracker", path, fn) -> Iterator[Finding]:
    if isinstance(node, ast.Assign):
        for target in node.targets:
            stored_long = isinstance(target, ast.Attribute) or (
                isinstance(target, ast.Subscript)
                and dotted(target.value) is not None
                and "." in (dotted(target.value) or "")
            )
            if stored_long and tracker.is_view_expr(node.value):
                where = dotted(target) or dotted(
                    getattr(target, "value", target)
                ) or "<target>"
                yield Finding(
                    VIEW_ESCAPE.rule_id, path, node.lineno,
                    f"view of a reusable buffer stored into {where} "
                    f"outlives `{fn.name}` — copy (`bytes(view)`) or keep "
                    "the holder's lifetime inside the buffer owner's",
                )
    elif isinstance(node, ast.Call):
        # self._held.append(view) / registry.add(view)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _CONTAINER_ADD
        ):
            recv = dotted(node.func.value)
            if recv is not None and "." in recv:
                for arg in node.args:
                    if tracker.is_view_expr(arg):
                        yield Finding(
                            VIEW_ESCAPE.rule_id, path, node.lineno,
                            f"view of a reusable buffer appended to "
                            f"{recv} outlives `{fn.name}` — copy it or "
                            "bound the container's lifetime",
                        )


def _closure_escapes(fn, tracker: "_ViewTracker", path) -> Iterator[Finding]:
    if not tracker.views:
        return
    for node in _walk_same_function_with_nested_heads(fn):
        nested = None
        sink = None
        if isinstance(node, ast.Call):
            fname = dotted(node.func) or ""
            if fname.split(".")[-1] in _SCHEDULERS:
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        nested, sink = arg, fname
        if nested is None:
            continue
        captured = {
            n.id
            for n in ast.walk(nested)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        } & tracker.views
        for name in sorted(captured):
            yield Finding(
                VIEW_ESCAPE.rule_id, path, node.lineno,
                f"closure scheduled via {sink} captures view {name!r} of "
                "a reusable buffer — the callback runs after the frame "
                "(and possibly the buffer's reuse); copy before capture",
            )


def _walk_same_function_with_nested_heads(fn) -> Iterator[ast.AST]:
    """Like _walk_same_function but yields (without entering) nested
    defs/lambdas so closure sinks can inspect them."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------- GL110 use-after-donate


def _donating_callables(tree: ast.Module) -> dict[str, tuple[set, set]]:
    """name -> (donated positional indices, donated argnames) for
    module functions jitted with donation: decorator form
    (@partial(jax.jit, donate_argnums=...)) and wrapper assignment form
    (g = jax.jit(f, donate_argnums=...))."""
    from .rules import _jit_kwargs, _literal_ints, _literal_names

    out: dict[str, tuple[set, set]] = {}

    def record(name: str, kw: dict) -> None:
        nums = _literal_ints(kw.get("donate_argnums", ast.Constant(value=None)))
        names = _literal_names(
            kw.get("donate_argnames", ast.Constant(value=None))
        )
        if nums or names:
            out[name] = (set(nums or ()), set(names or ()))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                kw = _jit_kwargs(deco)
                if kw is not None:
                    record(node.name, kw)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fname = dotted(node.value.func)
            if fname in ("jax.jit", "jit") and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                record(
                    node.targets[0].id,
                    {k.arg: k.value for k in node.value.keywords if k.arg},
                )
    return out


def check_use_after_donate(tree: ast.Module, path: str) -> Iterator[Finding]:
    donators = _donating_callables(tree)
    if not donators:
        return
    for fn in _functions(tree):
        # events per donated NAME: (line, kind) kind in {donate, bind, load}
        donations: list[tuple[int, str, str]] = []  # (line, name, callee)
        binds: dict[str, list[int]] = {}
        loads: dict[str, list[tuple[int, ast.Name]]] = {}
        donated_arg_nodes: set[int] = set()
        for node in _walk_same_function(fn):
            if isinstance(node, ast.Call):
                fname = dotted(node.func) or ""
                callee = fname.split(".")[-1]
                if callee in donators:
                    idxs, names = donators[callee]
                    picked: list[ast.AST] = [
                        node.args[i] for i in idxs if i < len(node.args)
                    ] + [
                        k.value for k in node.keywords if k.arg in names
                    ]
                    for arg in picked:
                        if isinstance(arg, ast.Name):
                            donations.append((node.lineno, arg.id, callee))
                            donated_arg_nodes.add(id(arg))
            elif isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append((node.lineno, node))
                else:  # Store / Del rebinds the name
                    binds.setdefault(node.id, []).append(node.lineno)
        for line, name, callee in donations:
            rebind_after = [ln for ln in binds.get(name, ()) if ln >= line]
            first_rebind = min(rebind_after) if rebind_after else None
            # ascending source order: the window between the donating
            # call and the first rebind is where a load is a violation —
            # ast.walk yields loads in arbitrary order, so sort or the
            # rebind check can mask an earlier real use
            for load_line, load_node in sorted(
                loads.get(name, ()), key=lambda t: t[0]
            ):
                if load_line <= line or id(load_node) in donated_arg_nodes:
                    continue
                if first_rebind is not None and load_line >= first_rebind:
                    break  # rebound (e.g. `x = f(x)`): later uses are new
                yield Finding(
                    USE_AFTER_DONATE.rule_id, path, load_line,
                    f"{name!r} was donated to {callee}() on line {line} "
                    "and is referenced again here — the kernel may alias "
                    "its buffer as output; rebind the name to the call's "
                    "result or copy before the call",
                )
                break  # one finding per donation site


# --------------------------------------------------------- GL111 task-leak

_SPAWNERS = ("create_task", "ensure_future")


def _is_spawn(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func) or ""
    return name.split(".")[-1] in _SPAWNERS


def check_task_leak(tree: ast.Module, path: str) -> Iterator[Finding]:
    for fn in _functions(tree):
        nodes = list(_walk_same_function(fn))
        # name -> Load lines; retention means a load AFTER the spawn
        # assignment (a pre-assignment load of the same name — `t = None;
        # if t: ...; t = create_task(...)` — retains nothing).  Loop
        # bodies are the exception: a textually-earlier load there runs
        # after the assignment on the next iteration.
        load_lines: dict[str, list[int]] = {}
        cancel_lines: list[int] = []
        loop_spans: list[tuple[int, int]] = []
        for node in nodes:
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                load_lines.setdefault(node.id, []).append(node.lineno)
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                loop_spans.append(
                    (node.lineno, node.end_lineno or node.lineno)
                )
            if isinstance(node, ast.Call):
                cname = dotted(node.func) or ""
                if cname.endswith(".cancel"):
                    cancel_lines.append(node.lineno)

        def retained(name: str, assign_line: int) -> bool:
            in_loop = any(a <= assign_line <= b for a, b in loop_spans)
            return any(
                ln > assign_line or in_loop
                for ln in load_lines.get(name, ())
            )

        for node in nodes:
            # dropped handle: `asyncio.create_task(...)` as a statement
            if isinstance(node, ast.Expr) and _is_spawn(node.value):
                yield Finding(
                    TASK_LEAK.rule_id, path, node.lineno,
                    "task spawned and dropped — retain it (named set / "
                    "attribute) and attach a done-callback that logs the "
                    "exception, or await it",
                )
            # assigned but never used again: the GC can still collect it
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _is_spawn(node.value)
                and not retained(node.targets[0].id, node.lineno)
            ):
                yield Finding(
                    TASK_LEAK.rule_id, path, node.lineno,
                    f"task bound to {node.targets[0].id!r} but the name "
                    "is never read afterwards — the reference dies with "
                    "this frame; retain it somewhere owned or add a "
                    "done-callback",
                )
        # CancelledError swallowed outside a cancel-then-await pattern
        for node in nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _handles_cancelled(node):
                continue
            if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
                continue
            if any(ln < node.lineno for ln in cancel_lines):
                # this function cancelled something itself: awaiting the
                # cancelled task and eating ITS CancelledError is the
                # canonical shutdown pattern
                continue
            yield Finding(
                TASK_LEAK.rule_id, path, node.lineno,
                "except CancelledError neither re-raises nor follows a "
                "`.cancel()` this function issued — swallowing foreign "
                "cancellation turns shutdown into a hang; re-raise "
                "after cleanup",
            )


def _handles_cancelled(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(
        (dotted(e) or "").split(".")[-1] == "CancelledError" for e in elts
    )
