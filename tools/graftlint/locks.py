"""Static lock acquisition-order analysis (rule GL104).

Two passes over the concurrency-relevant modules (LOCK_SCOPE_PARTS):

  1. identity collection — every `threading.Lock/RLock/Condition()`
     assigned to `self.<attr>` (identity "ClassName.<attr>") or a
     module-level name (identity "module.<name>"), with its kind;
  2. per-function facts — which identities each function acquires
     directly (`with self._lock:`, `lock.acquire()`), and which calls it
     makes while holding each of them.

Call resolution is deliberately conservative (no type inference):

  * `self.foo()`   -> method foo of the enclosing class, if analyzed;
  * `foo()`        -> module-level function foo (same module first);
  * `<name>.foo()` -> the ONE analyzed method named foo when the name is
                      unambiguous across analyzed classes, else skipped;
  * compound receivers (`self._arrays.get(...)`) are skipped — guessing
    there is where name-based analysis starts lying.

Effective acquisitions propagate through the resolved call graph to a
fixpoint, then edges are: lock A -> every lock effectively acquired by
code reachable while A is held (direct nesting included).  A cycle in
that graph — including a self-edge on a non-reentrant Lock — is a
lock-order hazard the runtime lockwatch harness can only catch if the
schedule actually interleaves; here it fails at lint time.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from collections import defaultdict
from typing import Iterator

from .model import LOCK_ORDER, Finding
from .rules import dotted

# modules whose locks participate in the order graph (the EC serving
# stack named by the issue + the corpus so the seeded fixture fires)
LOCK_SCOPE_PARTS = (
    "seaweedfs_tpu/ops/rs_resident.py",
    "seaweedfs_tpu/serving/",
    "seaweedfs_tpu/storage/ec/",
    "seaweedfs_tpu/obs/trace.py",
    "seaweedfs_tpu/stats/cluster.py",
    "lint_corpus",
)

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}

# method names shared with builtin containers / stdlib objects: a dotted
# call ending in one of these (on a non-self receiver) is far more
# likely dict/list/queue traffic than an analyzed method — resolving it
# by bare name would invent lock edges out of `self._arrays.get(...)`
_GENERIC_METHODS = {
    "get", "put", "pop", "popitem", "set", "add", "clear", "items",
    "keys", "values", "update", "setdefault", "append", "appendleft",
    "extend", "insert", "remove", "discard", "sort", "copy", "index",
    "count", "join", "split", "strip", "read", "write", "close", "open",
    "result", "submit", "cancel", "done", "wait", "notify", "notify_all",
    "acquire", "release", "locked", "start", "is_alive", "move_to_end",
    "get_nowait", "put_nowait", "empty", "full", "qsize", "is_set",
    "inc", "dec", "observe", "labels", "collect", "info", "debug",
    "warning", "error", "exception",
}


def in_lock_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(part in p for part in LOCK_SCOPE_PARTS)


def cycles_from_edges(graph: dict) -> list[list[str]]:
    """Elementary cycles of a {node: {successor}} order graph, each
    rendered as [a, b, ..., a].  Shared by this static pass and the
    runtime lockwatch harness (tests/lockwatch.py) so a traversal fix
    reaches both."""
    seen: set = set()
    out: list[list[str]] = []
    found: set = set()

    def dfs(node: str, stack: list[str], on_stack: set) -> None:
        seen.add(node)
        stack.append(node)
        on_stack.add(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in found:
                    found.add(key)
                    out.append(cyc)
            elif nxt not in seen:
                dfs(nxt, stack, on_stack)
        stack.pop()
        on_stack.remove(node)

    for node in sorted(graph):
        if node not in seen:
            dfs(node, [], set())
    return out


@dataclasses.dataclass
class FuncFacts:
    qualname: str                       # "module:Class.method" | "module:fn"
    direct: set = dataclasses.field(default_factory=set)
    # calls made while holding a given identity: {identity: {callee-key}}
    calls_holding: dict = dataclasses.field(
        default_factory=lambda: defaultdict(set)
    )
    # all resolved calls (for transitive acquisition propagation)
    calls: set = dataclasses.field(default_factory=set)
    # where each direct acquisition happens (identity -> first lineno)
    sites: dict = dataclasses.field(default_factory=dict)


class _ModuleScan(ast.NodeVisitor):
    """Collect lock identities + per-function facts for one module."""

    def __init__(self, module: str, path: str, analysis: "LockAnalysis"):
        self.module = module
        self.path = path
        self.analysis = analysis
        self._class: str | None = None
        self._func: FuncFacts | None = None
        self._held: list[str] = []  # identity stack in the current func

    # ---------------------------------------------------- identities
    def _lock_kind(self, value: ast.AST) -> str | None:
        if isinstance(value, ast.Call):
            return _LOCK_CTORS.get(dotted(value.func) or "")
        return None

    def _record_assign(self, target: ast.AST, value: ast.AST, line: int):
        kind = self._lock_kind(value)
        if kind is None:
            return
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and self._class
        ):
            ident = f"{self._class}.{target.attr}"
        elif isinstance(target, ast.Name) and self._func is None:
            ident = f"{self.module}.{target.id}"
        else:
            return
        self.analysis.kinds[ident] = kind
        # real file path + declaration line: findings anchor here, so a
        # `# graftlint: allow(lock-order)` above the declaration waives
        self.analysis.decl_sites[ident] = (self.path, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_assign(t, node.value, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_assign(node.target, node.value, node.lineno)
        self.generic_visit(node)

    # ------------------------------------------------------- scoping
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = prev

    def _visit_func(self, node) -> None:
        if self._func is not None:
            # nested function: analyze within the same facts (it runs on
            # the same thread unless dispatched, and losing its
            # acquisitions would under-report)
            self.generic_visit(node)
            return
        qual = (
            f"{self.module}:{self._class}.{node.name}"
            if self._class else f"{self.module}:{node.name}"
        )
        self._func = FuncFacts(qual)
        self.analysis.funcs[qual] = self._func
        key = node.name if self._class is None else f"{self._class}.{node.name}"
        self.analysis.by_name[node.name].add(qual)
        self.analysis.by_qual_name[key].add(qual)
        self.generic_visit(node)
        self._func = None
        self._held = []

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # --------------------------------------------------- acquisitions
    def _identify_lock_expr(self, expr: ast.AST) -> str | None:
        """Identity acquired by `with <expr>:` / `<expr>.acquire()`."""
        name = dotted(expr)
        if name is None:
            return None
        if name.startswith("self.") and self._class:
            ident = f"{self._class}.{name[5:]}"
            if ident in self.analysis.kinds:
                return ident
            return None
        mod_ident = f"{self.module}.{name}"
        if mod_ident in self.analysis.kinds:
            return mod_ident
        return None

    def visit_With(self, node: ast.With) -> None:
        if self._func is None:
            self.generic_visit(node)
            return
        acquired: list[str] = []
        for item in node.items:
            # the item expression runs under whatever is already held at
            # this point (locks from enclosing withs AND earlier items of
            # this one) — visit it BEFORE noting its own acquisition so
            # `with A, foo():` records A -> locks(foo)
            self.visit(item.context_expr)
            ident = self._identify_lock_expr(item.context_expr)
            if ident is None:
                continue
            self._note_acquire(ident, item.context_expr.lineno
                               if hasattr(item.context_expr, "lineno")
                               else node.lineno)
            acquired.append(ident)
            self._held.append(ident)
        for stmt in node.body:
            self.visit(stmt)
        for ident in acquired:
            self._held.remove(ident)

    visit_AsyncWith = visit_With

    def _note_acquire(self, ident: str, line: int) -> None:
        assert self._func is not None
        self._func.direct.add(ident)
        self._func.sites.setdefault(ident, line)
        for held in self._held:
            if held != ident:
                self.analysis.direct_edges[(held, ident)] = (
                    self._func.qualname, line
                )
            elif self.analysis.kinds.get(ident) == "Lock":
                self.analysis.self_edges[ident] = (self._func.qualname, line)

    # ---------------------------------------------------------- calls
    def visit_Call(self, node: ast.Call) -> None:
        if self._func is not None:
            name = dotted(node.func)
            if name is not None:
                if name.endswith(".acquire"):
                    ident = self._identify_lock_expr(
                        node.func.value  # type: ignore[attr-defined]
                    )
                    if ident is not None:
                        self._note_acquire(ident, node.lineno)
                        self.generic_visit(node)
                        return
                key = self._resolve_call_key(name)
                if key is not None:
                    self._func.calls.add((key, node.lineno))
                    for held in self._held:
                        self._func.calls_holding[held].add(
                            (key, node.lineno)
                        )
        self.generic_visit(node)

    def _resolve_call_key(self, name: str) -> str | None:
        """Map a dotted call to a resolution key handled in pass 2:
        'm:<module>:<fn>' / 'c:<Class>.<meth>' / 'u:<meth>'."""
        parts = name.split(".")
        if len(parts) == 1:
            return f"m:{self.module}:{parts[0]}"
        if parts[0] == "self" and len(parts) == 2 and self._class:
            return f"c:{self._class}.{parts[1]}"
        # <name>.<meth> (and compound receivers — `cache.pipeline.slot()`
        # must reach slot()): resolvable only when the method name is
        # unambiguous among analyzed classes AND not a generic
        # container/stdlib verb — `self._arrays.get(...)` naming
        # dict.get must not alias DeviceShardCache.get
        if parts[-1] in _GENERIC_METHODS:
            return None
        return f"u:{parts[-1]}"


class LockAnalysis:
    def __init__(self) -> None:
        self.kinds: dict[str, str] = {}
        self.decl_sites: dict[str, tuple[str, int]] = {}
        self.funcs: dict[str, FuncFacts] = {}
        self.by_name: dict[str, set] = defaultdict(set)
        self.by_qual_name: dict[str, set] = defaultdict(set)
        self.direct_edges: dict[tuple, tuple] = {}
        self.self_edges: dict[str, tuple] = {}

    # ------------------------------------------------------ resolution
    def _targets(self, key: str) -> list[FuncFacts]:
        kind, _, rest = key.partition(":")
        if kind == "m":
            module, _, fn = rest.partition(":")
            qual = f"{module}:{fn}"
            if qual in self.funcs:
                return [self.funcs[qual]]
            # fall back to a unique same-named module function elsewhere
            quals = {
                q for q in self.by_name.get(fn, ())
                if ":" in q and "." not in q.split(":", 1)[1]
            }
            return [self.funcs[q] for q in quals] if len(quals) == 1 else []
        if kind == "c":
            quals = self.by_qual_name.get(rest, set())
            return [self.funcs[q] for q in quals]
        if kind == "u":
            quals = {
                q for q in self.by_name.get(rest, ())
                if "." in q.split(":", 1)[1]  # methods only
            }
            if len(quals) == 1:
                return [self.funcs[quals.pop()]]
        return []

    def effective_acquires(self) -> dict[str, set]:
        """Fixpoint: locks acquired by each function directly or via any
        resolved callee (nested-call depth unbounded, cycles safe)."""
        eff = {q: set(f.direct) for q, f in self.funcs.items()}
        changed = True
        while changed:
            changed = False
            for q, f in self.funcs.items():
                for key, _line in f.calls:
                    for callee in self._targets(key):
                        add = eff[callee.qualname] - eff[q]
                        if add:
                            eff[q].update(add)
                            changed = True
        return eff

    def edges(self) -> dict[tuple, tuple]:
        """(A, B) -> (where, line): B is acquired while A is held."""
        out = dict(self.direct_edges)
        eff = self.effective_acquires()
        for q, f in self.funcs.items():
            for held, calls in f.calls_holding.items():
                for key, line in calls:
                    for callee in self._targets(key):
                        for acquired in eff[callee.qualname]:
                            if acquired == held:
                                if self.kinds.get(held) == "Lock":
                                    self.self_edges.setdefault(
                                        held, (q, line)
                                    )
                                continue
                            out.setdefault(
                                (held, acquired), (q, line)
                            )
        return out



def analyze(files: dict[str, ast.Module]) -> LockAnalysis:
    """files: {path: parsed tree} — only lock-scope files are scanned."""
    analysis = LockAnalysis()
    for path, tree in sorted(files.items()):
        if not in_lock_scope(path):
            continue
        module = os.path.splitext(os.path.basename(path))[0]
        _ModuleScan(module, path, analysis).visit(tree)
    return analysis


def check_lock_order(files: dict[str, ast.Module]) -> Iterator[Finding]:
    analysis = analyze(files)
    # ONE edges() pass: it runs the effective-acquisition fixpoint and
    # (as a side effect) completes self_edges — both the cycle graph
    # and the self-edge findings below read from this single result
    edge_sites = analysis.edges()
    graph: dict[str, set] = defaultdict(set)
    for (a, b) in edge_sites:
        graph[a].add(b)
    for cyc in cycles_from_edges(graph):
        legs = " -> ".join(cyc)
        first = edge_sites.get((cyc[0], cyc[1]))
        where = f" (first leg in {first[0]}, line {first[1]})" if first else ""
        path, line = analysis.decl_sites.get(cyc[0], ("lock-graph", 0))
        yield Finding(
            LOCK_ORDER.rule_id, path, line,
            f"lock acquisition-order cycle: {legs}{where} — pick one "
            "global order for these locks and release before crossing "
            "(a waiver above this lock's declaration suppresses)",
        )
    for ident, (qual, line) in analysis.self_edges.items():
        path, decl_line = analysis.decl_sites.get(ident, ("lock-graph", 0))
        yield Finding(
            LOCK_ORDER.rule_id, path, decl_line,
            f"non-reentrant Lock {ident} may be re-acquired while held "
            f"(in {qual}, line {line}) — use RLock or restructure",
        )
