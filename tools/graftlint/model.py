"""Finding + rule registry for graftlint.

Every rule registers itself here with an id, a one-line summary, and a
tiny example of what it catches; the README's "Static analysis" table is
GENERATED from this registry (tools.graftlint --doc), and the doc-drift
test fails when the README falls behind — the same honesty contract the
metrics table already lives under.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str          # rule id, e.g. "GL101"
    path: str          # file the finding is in (repo-relative when possible)
    line: int          # 1-based line number (0 for file-level findings)
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    """Registry entry: identity + the doc-table row."""

    rule_id: str       # stable id (GLnnn)
    name: str          # kebab-case name usable in waiver comments
    summary: str       # one line: what it catches
    example: str       # a minimal triggering snippet (doc table column)


# ordered registry: the README table renders in this order
RULES: list[Rule] = []
_BY_ID: dict[str, Rule] = {}
_BY_NAME: dict[str, Rule] = {}


def register(rule_id: str, name: str, summary: str, example: str) -> Rule:
    rule = Rule(rule_id, name, summary, example)
    RULES.append(rule)
    _BY_ID[rule_id] = rule
    _BY_NAME[name] = rule
    return rule


def rule_by_id(rule_id: str) -> Rule:
    return _BY_ID[rule_id]


ASYNC_BLOCKING = register(
    "GL101",
    "async-blocking",
    "blocking call (time.sleep, sync file/socket IO, Future.result, "
    "subprocess) inside an `async def` body without to_thread/executor "
    "dispatch — stalls the event loop for every connection it serves",
    "async def h(r): time.sleep(1)",
)
DEVICE_SYNC = register(
    "GL102",
    "device-sync",
    "implicit device->host transfer (np.asarray/.item()/jnp truthiness) "
    "in a serving hot-path module outside a traced d2h_copy span and "
    "without an explicit waiver — hidden syncs stall the device pipeline",
    "out = np.asarray(device_arr)  # in serving/",
)
JIT_STATIC = register(
    "GL103",
    "jit-static-args",
    "jax.jit static_argnums/static_argnames/donate_argnums that don't "
    "match the wrapped function's signature (unknown name, out-of-range "
    "or donated-and-static index) — fails at trace time or silently "
    "never donates",
    "@partial(jax.jit, static_argnames=('typo',))",
)
LOCK_ORDER = register(
    "GL104",
    "lock-order",
    "cycle in the static lock acquisition-order graph across the EC "
    "serving stack (DeviceShardCache, DevicePipeline, dispatcher, bulk "
    "executor) — an AB/BA ordering that can deadlock under load",
    "with A: take_B()  /  with B: take_A()",
)
METRIC_REGISTRY = register(
    "GL105",
    "metric-registry",
    "SeaweedFS_* series literal that is not pre-registered in "
    "stats/metrics.py / stats/cluster.py (or a series declared outside "
    "them) — the runtime drift tests only catch this once the code runs",
    'g("SeaweedFS_bogus_total")',
)
STAGE_REGISTRY = register(
    "GL106",
    "stage-registry",
    "trace-stage literal passed to obs span()/record_span() that is not "
    "in stats.metrics.TRACE_STAGES — the stage histogram would grow an "
    "undocumented, un-pre-registered label at runtime",
    'with obs.span("bogus_stage"):',
)
PROTO_DRIFT = register(
    "GL107",
    "proto-drift",
    "field name/number mismatch between pb/*.proto and the "
    "descriptor-mutated *_pb2.py modules (either direction) — the .proto "
    "is the wire contract, the pb2 is what actually serializes",
    "master.proto says `= 7`, master_pb2 says `= 9`",
)
SILENT_SWALLOW = register(
    "GL108",
    "no-silent-swallow",
    "broad `except Exception/BaseException/bare:` whose body is only "
    "`pass` — errors vanish without a log line; narrow exception types "
    "stay allowed",
    "except Exception:\\n    pass",
)
VIEW_ESCAPE = register(
    "GL109",
    "view-escape",
    "a memoryview/ndarray view derived from a reusable or mutable "
    "buffer (bytearray, np.empty staging, an arena attribute) escapes "
    "the deriving function — stored into an object field, container, "
    "or a scheduled closure — so buffer reuse/free mutates bytes the "
    "holder still reads (the zero-copy hazard class)",
    "self.cache[k] = memoryview(staging)[a:b]",
)
USE_AFTER_DONATE = register(
    "GL110",
    "use-after-donate",
    "an array passed at a donate_argnums/donate_argnames position of a "
    "jitted call is referenced again afterwards in the same function "
    "without being rebound — the donated buffer may already be aliased "
    "by the kernel's output",
    "y = f(buf); buf[0]  # buf was donated to f",
)
TASK_LEAK = register(
    "GL111",
    "task-leak",
    "an asyncio.create_task/ensure_future result that is neither "
    "awaited, retained, nor given a done-callback (fire-and-forget "
    "tasks can be GC'd mid-flight and their exceptions vanish), or an "
    "`except CancelledError` that neither re-raises nor follows a "
    "`.cancel()` this function itself issued",
    "asyncio.create_task(loop())  # result dropped",
)
FLAG_DRIFT = register(
    "GL112",
    "flag-drift",
    "an `-ec.*`/`-obs.*` CLI flag drifted from its contract: declared "
    "without a README flag-table row, a serving/qos/bulk/obs flag its "
    "config module never names, a README row or config mention with no "
    "declaring add_argument — both directions checked",
    'add_argument("-ec.qos.bogusKnob")  # no README row, no config',
)
UNUSED_WAIVER = register(
    "GL113",
    "unused-waiver",
    "a `# graftlint: allow(<rule>)` comment that no longer suppresses "
    "any finding — stale waivers hide future violations at the exact "
    "line a reviewer already stopped reading",
    "# graftlint: allow(async-blocking): stale — nothing here blocks",
)
UNBOUNDED_RPC = register(
    "GL114",
    "unbounded-rpc",
    "a cross-node RPC call site (proto rpc method name) in the EC "
    "serving/repair/mount path without a `timeout=` argument and "
    "outside a bounded wrapper (asyncio.wait_for / "
    "faultpolicy.retry_rpc) — one hung peer pins the caller forever; "
    "deliberately unbounded long-lived streams carry a reasoned waiver",
    "await stub.VolumeEcShardsCopy(req)  # no timeout",
)
UNSHARDED_DEVICE_PUT = register(
    "GL115",
    "unsharded-device-put",
    "a jax.device_put in the serving/ops/parallel scope without an "
    "explicit sharding/device argument — the buffer lands on the "
    "default device regardless of the mesh layout, silently crowding "
    "device 0 and breaking the per-device budget accounting the r19 "
    "sharded residency relies on",
    "arr = jax.device_put(padded)  # no sharding/device",
)
UNTAGGED_DEVICE_DISPATCH = register(
    "GL116",
    "untagged-device-dispatch",
    "a device dispatch primitive (_dispatch_call, "
    "apply_matrix_device_flat, _scrub_call*, _scrub_all_call) invoked "
    "outside a devledger workload/device tagging context — its busy "
    "time lands in the `untagged` ledger class and the per-workload "
    "attribution the contention timeline depends on silently leaks",
    "arr = _dispatch_call(...)  # no devledger.workload/device",
)
STAGE_DRIFT = register(
    "GL117",
    "stage-drift",
    "a TRACE_STAGES entry with no literal span()/record_span() call "
    "site anywhere in the linted tree — the critical-path attribution "
    "(obs/critpath.py) maps every stage to a latency segment, so a "
    "declared-but-never-recorded stage is a dead row in the README "
    "table and a segment that silently reads as zero",
    'TRACE_STAGES = (..., "ghost_stage")  # nothing records it',
)
PROCESS_LOCAL_DEVICE = register(
    "GL118",
    "process-local-device-assumption",
    "a direct jax.devices()/jax.local_devices()/jax.device_count()/"
    "jax.local_device_count() call in the placement-policy scope "
    "(parallel/serving/ops) instead of the parallel.mesh helpers — on "
    "a multi-process mesh the local and global device sets differ, so "
    "a mesh or budget sized off the raw enumeration silently shrinks "
    "to one host's chips (or double-counts the pod's)",
    "n = len(jax.devices())  # process-local on a pod; use parallel.mesh",
)


def rule_table_markdown() -> str:
    """The README 'Static analysis' rule table, generated from the
    registry (id, name, what it catches, example)."""
    lines = [
        "| id | rule | catches | example |",
        "| --- | --- | --- | --- |",
    ]
    for r in RULES:
        example = r.example.replace("|", "\\|")
        lines.append(
            f"| `{r.rule_id}` | `{r.name}` | {r.summary} | `{example}` |"
        )
    return "\n".join(lines)
