"""Strict-typing gate: run mypy over the incremental adoption list.

The adoption list and strictness live in mypy.ini (repo root) — this
runner just invokes mypy with that config when the interpreter has it
and reports the outcome.  The container this repo targets does not ship
mypy (and nothing may be pip-installed), so absence is a SKIP, not a
failure: the gate enforces strictness wherever mypy exists (dev
machines, CI images that carry it) without making the lint run depend
on an uninstallable tool.
"""
from __future__ import annotations

import importlib.util
import os
import subprocess
import sys


def mypy_available() -> bool:
    return importlib.util.find_spec("mypy") is not None


def run_mypy(repo_root: str) -> tuple[int, str]:
    """-> (exit code, output).  Exit 0 when clean OR when mypy is not
    installed (reported as a skip in the output)."""
    config = os.path.join(repo_root, "mypy.ini")
    if not os.path.exists(config):
        return 1, "mypy gate: mypy.ini not found at repo root"
    if not mypy_available():
        return 0, "mypy gate: SKIPPED (mypy not installed in this env)"
    proc = subprocess.run(
        [sys.executable, "-m", "mypy", "--config-file", config],
        capture_output=True,
        text=True,
        cwd=repo_root,
    )
    out = (proc.stdout + proc.stderr).strip()
    return proc.returncode, f"mypy gate:\n{out}" if out else "mypy gate: ok"
