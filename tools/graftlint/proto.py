"""Proto drift rule (GL107): pb/*.proto vs the descriptor-mutated
*_pb2.py modules.

This repo regenerates pb2 modules WITHOUT protoc (the container has no
grpc_tools): new fields are appended by mutating the serialized
FileDescriptorProto and rewriting the module around the new blob.  That
workflow makes it easy for the human-readable .proto to fall behind the
pb2 that actually serializes (or vice versa when someone edits the
.proto and forgets the mutation).  This rule compares, per message, the
field name -> number maps in both directions; any mismatch is a wire
contract drift.

The .proto side is parsed with a small brace-tracking parser (proto3
subset actually used here: messages, nested messages, repeated/optional
fields, map<k,v> fields); the pb2 side is read from the imported
module's DESCRIPTOR — pure metadata, no service/server code runs.
"""
from __future__ import annotations

import importlib
import os
import re
from typing import Iterator

from .model import PROTO_DRIFT, Finding

_FIELD_RE = re.compile(
    r"^(?:repeated\s+|optional\s+)?"
    r"(?:map\s*<[^>]+>|[A-Za-z_][\w.]*)\s+"
    r"([a-z_][\w]*)\s*=\s*(\d+)\s*(?:\[[^\]]*\])?$"
)
_MSG_RE = re.compile(r"^message\s+([A-Za-z_]\w*)$")


def parse_proto(text: str) -> dict[str, dict[str, int]]:
    """{Message (dotted for nested): {field_name: number}}.

    Token-driven (statements split on `{`/`}`/`;`) rather than
    line-driven, so one-line bodies like
    `message M { uint32 id = 1; }` parse the same as the multi-line
    form.  Blocks that are not messages (service/enum/oneof/rpc bodies)
    are tracked for brace balance and their statements skipped."""
    text = re.sub(r"//[^\n]*", "", text)
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    out: dict[str, dict[str, int]] = {}
    stack: list[str | None] = []  # None = non-message block
    buf: list[str] = []
    for tok in re.split(r"([{};])", text):
        if tok == "{":
            header = " ".join("".join(buf).split())
            buf = []
            m = _MSG_RE.match(header)
            if m:
                parent = next(
                    (s for s in reversed(stack) if s is not None), None
                )
                name = f"{parent}.{m.group(1)}" if parent else m.group(1)
                out[name] = {}
                stack.append(name)
            else:
                stack.append(None)
        elif tok == "}":
            buf = []
            if stack:
                stack.pop()
        elif tok == ";":
            stmt = " ".join("".join(buf).split())
            buf = []
            # fields belong to the INNERMOST block only when it is a
            # message (oneof members would need the enclosing message —
            # none of this repo's protos use oneof)
            if stack and stack[-1] is not None:
                f = _FIELD_RE.match(stmt)
                if f:
                    out[stack[-1]][f.group(1)] = int(f.group(2))
        else:
            buf.append(tok)
    return out


def _walk_descriptor(msg, prefix: str, out: dict) -> None:
    out[prefix] = {f.name: f.number for f in msg.fields}
    for nested in msg.nested_types:
        if nested.GetOptions().map_entry:
            continue  # synthesized map-entry message; the map field
            # itself already carries the user-visible name/number
        _walk_descriptor(nested, f"{prefix}.{nested.name}", out)


def fields_from_pb2(module) -> dict[str, dict[str, int]]:
    out: dict[str, dict[str, int]] = {}
    for name, msg in module.DESCRIPTOR.message_types_by_name.items():
        _walk_descriptor(msg, name, out)
    return out


def check_proto_dir(
    proto_dir: str, pb2_package: str = "seaweedfs_tpu.pb"
) -> Iterator[Finding]:
    """Compare every <stem>.proto in `proto_dir` against
    <pb2_package>.<stem>_pb2 (skipping stems with no pb2 module)."""
    for entry in sorted(os.listdir(proto_dir)):
        if not entry.endswith(".proto"):
            continue
        stem = entry[: -len(".proto")]
        path = os.path.join(proto_dir, entry)
        try:
            module = importlib.import_module(f"{pb2_package}.{stem}_pb2")
        except ImportError:
            yield Finding(
                PROTO_DRIFT.rule_id, path, 0,
                f"no generated module {pb2_package}.{stem}_pb2 for this "
                ".proto — regenerate (pb/generate.sh / descriptor "
                "mutation) or remove the schema",
            )
            continue
        with open(path, encoding="utf-8") as f:
            proto_fields = parse_proto(f.read())
        pb2_fields = fields_from_pb2(module)
        for msg in sorted(set(proto_fields) | set(pb2_fields)):
            in_proto = proto_fields.get(msg)
            in_pb2 = pb2_fields.get(msg)
            if in_proto is None:
                yield Finding(
                    PROTO_DRIFT.rule_id, path, 0,
                    f"message {msg} exists in {stem}_pb2 but not in "
                    f"{entry} — the .proto fell behind a descriptor "
                    "mutation",
                )
                continue
            if in_pb2 is None:
                yield Finding(
                    PROTO_DRIFT.rule_id, path, 0,
                    f"message {msg} exists in {entry} but not in "
                    f"{stem}_pb2 — regenerate the pb2 module",
                )
                continue
            for fname in sorted(set(in_proto) | set(in_pb2)):
                a, b = in_proto.get(fname), in_pb2.get(fname)
                if a != b:
                    yield Finding(
                        PROTO_DRIFT.rule_id, path, 0,
                        f"{msg}.{fname}: .proto says "
                        f"{'absent' if a is None else a}, {stem}_pb2 says "
                        f"{'absent' if b is None else b} — field "
                        "name/number drift on the wire contract",
                    )
