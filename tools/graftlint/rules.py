"""Per-file AST rules (stdlib ast only — no third-party linter deps).

Each check_* function takes the parsed tree plus file context and yields
Finding objects.  Waiver comments (`# graftlint: allow(<rule-name>)` on
the flagged line or the line above, with a reason) are applied by the
engine, not here — rules stay pure detectors.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator

from .model import (
    ASYNC_BLOCKING,
    DEVICE_SYNC,
    JIT_STATIC,
    METRIC_REGISTRY,
    PROCESS_LOCAL_DEVICE,
    SILENT_SWALLOW,
    STAGE_REGISTRY,
    UNBOUNDED_RPC,
    UNSHARDED_DEVICE_PUT,
    UNTAGGED_DEVICE_DISPATCH,
    Finding,
)

# ------------------------------------------------------------------ helpers


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, None for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ------------------------------------------------------- GL101 async-blocking

# call roots that block the calling thread.  The event loop serves every
# connection on one thread: a single blocking call here is a full-stop
# for the whole server, which is exactly what the dispatcher's
# to_thread hops exist to avoid.
_BLOCKING_EXACT = {
    "time.sleep",
    "os.pread", "os.preadv", "os.pwrite", "os.pwritev", "os.fsync",
    "os.fdatasync", "os.sendfile", "os.read", "os.write",
    "socket.create_connection", "socket.getaddrinfo",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "urllib.request.urlopen",
}
_BLOCKING_PREFIX = ("requests.",)
# open() staged reads/writes and Future.result() are attribute-position
# agnostic: flag the builtin name / the method name.
_BLOCKING_METHODS = {"result"}  # fut.result() — concurrent.futures sync wait
# methods on a sync file handle kept alive across awaits (the
# `f = await to_thread(open, ...)` pattern): calling these directly in
# the async body blocks the loop just like the open() would have
_HANDLE_METHODS = {
    "read", "readline", "readlines", "write", "writelines", "seek",
    "truncate", "flush", "close",
}


def _opens_file(value: ast.AST) -> bool:
    """True for `open(...)`, `await asyncio.to_thread(open, ...)`, and
    `await loop.run_in_executor(ex, open, ...)` — the expressions that
    bind a SYNC file handle to a name in an async body."""
    if isinstance(value, ast.Await):
        value = value.value
    if not isinstance(value, ast.Call):
        return False
    name = dotted(value.func) or ""
    if name == "open":
        return True
    if name.endswith("to_thread") and value.args:
        return dotted(value.args[0]) == "open"
    if name.endswith("run_in_executor") and len(value.args) >= 2:
        return dotted(value.args[1]) == "open"
    return False


def check_async_blocking(tree: ast.Module, path: str) -> Iterator[Finding]:
    for outer in ast.walk(tree):
        if not isinstance(outer, ast.AsyncFunctionDef):
            continue
        nodes = list(_walk_same_function(outer))
        handles = {
            n.targets[0].id
            for n in nodes
            if isinstance(n, ast.Assign)
            and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
            and _opens_file(n.value)
        }
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            hit = None
            if name in _BLOCKING_EXACT:
                hit = name
            elif name == "open":
                hit = "open()"
            elif name and name.startswith(_BLOCKING_PREFIX):
                hit = name
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in handles
                and node.func.attr in _HANDLE_METHODS
            ):
                hit = (
                    f"{node.func.value.id}.{node.func.attr}() on a sync "
                    "file handle"
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS
                and len(node.args) + len(node.keywords) <= 1
                and all(kw.arg == "timeout" for kw in node.keywords)
            ):
                # zero-arg result() or result(timeout=...): bounded is
                # still a blocked event loop for up to the timeout
                hit = f"<obj>.{node.func.attr}()"
            if hit:
                yield Finding(
                    ASYNC_BLOCKING.rule_id, path, node.lineno,
                    f"blocking call {hit} inside `async def "
                    f"{outer.name}` — dispatch via asyncio.to_thread / "
                    "run_in_executor instead",
                )


def _walk_same_function(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested function /
    lambda scopes (their bodies run in whatever context CALLS them —
    run_in_executor lambdas are the common legitimate case)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------- GL102 device-sync

# modules on the device serving hot path: an implicit D2H here stalls
# the pipeline mid-batch.  lint_corpus is in the set so the seeded
# fixture exercises the rule without faking paths.
HOT_PATH_PARTS = (
    "seaweedfs_tpu/serving/",
    "seaweedfs_tpu/ops/rs_resident.py",
    "seaweedfs_tpu/storage/ec/",
    "lint_corpus",
)

_D2H_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
              "jax.device_get"}
_JNP_ROOTS = ("jnp.", "jax.numpy.")


def is_hot_path(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(part in p for part in HOT_PATH_PARTS)


class _DeviceSyncVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []
        self._span_depth = 0  # inside a `with *.span("d2h_copy")` block

    # -- span tracking ------------------------------------------------
    def _with_d2h_span(self, node: ast.With) -> bool:
        for item in node.items:
            call = item.context_expr
            if not isinstance(call, ast.Call):
                continue
            name = dotted(call.func) or ""
            if name.endswith("span") and call.args:
                if _str_const(call.args[0]) == "d2h_copy":
                    return True
        return False

    def visit_With(self, node: ast.With) -> None:
        if self._with_d2h_span(node):
            self._span_depth += 1
            self.generic_visit(node)
            self._span_depth -= 1
        else:
            self.generic_visit(node)

    # -- detectors ----------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted(node.func)
        if name in _D2H_CALLS and not any(
            kw.arg == "dtype" for kw in node.keywords
        ):
            # dtype= marks host-side coercion/staging (np.asarray of
            # bytes); a device array fetch never re-types
            if not self._span_depth:
                self.findings.append(Finding(
                    DEVICE_SYNC.rule_id, self.path, node.lineno,
                    f"{name}(...) in a hot-path module is an implicit "
                    "device->host transfer: wrap it in an obs span "
                    '("d2h_copy") or waive it with '
                    "`# graftlint: allow(device-sync): <reason>`",
                ))
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
            and not self._span_depth
        ):
            self.findings.append(Finding(
                DEVICE_SYNC.rule_id, self.path, node.lineno,
                ".item() in a hot-path module is a synchronous "
                "device->host scalar fetch: hoist it off the serving "
                "path or waive with a reason",
            ))
        self.generic_visit(node)

    def _check_truthiness(self, test: ast.AST, lineno: int) -> None:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                name = dotted(sub.func) or ""
                if name.startswith(_JNP_ROOTS):
                    self.findings.append(Finding(
                        DEVICE_SYNC.rule_id, self.path, lineno,
                        f"branching on {name}(...) forces a blocking "
                        "device sync to evaluate the condition — "
                        "compute the predicate on host or keep it in "
                        "the jit",
                    ))
                    return

    def visit_If(self, node: ast.If) -> None:
        self._check_truthiness(node.test, node.lineno)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_truthiness(node.test, node.lineno)
        self.generic_visit(node)


def check_device_sync(tree: ast.Module, path: str) -> Iterator[Finding]:
    if not is_hot_path(path):
        return
    v = _DeviceSyncVisitor(path)
    v.visit(tree)
    yield from v.findings


# ------------------------------------------------------ GL103 jit-static-args


def _jit_kwargs(deco: ast.AST) -> dict | None:
    """static/donate kwargs of a jax.jit decorator form, else None.
    Handles @functools.partial(jax.jit, ...) / @partial(jax.jit, ...)
    and @jax.jit(...) (direct call form)."""
    if not isinstance(deco, ast.Call):
        return None
    name = dotted(deco.func)
    if name in ("functools.partial", "partial"):
        if not deco.args or dotted(deco.args[0]) not in ("jax.jit", "jit"):
            return None
    elif name not in ("jax.jit", "jit"):
        return None
    return {kw.arg: kw.value for kw in deco.keywords if kw.arg}


def _literal_names(node: ast.AST) -> list[str] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            s = _str_const(elt)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def _literal_ints(node: ast.AST) -> list[int] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, int)
            ):
                return None
            out.append(elt.value)
        return out
    return None


def check_jit_static(tree: ast.Module, path: str) -> Iterator[Finding]:
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            kw = _jit_kwargs(deco)
            if kw is None:
                continue
            args = node.args
            positional = [a.arg for a in args.posonlyargs + args.args]
            all_names = positional + [a.arg for a in args.kwonlyargs]
            static_idx: set[int] = set()
            for key in ("static_argnames",):
                if key in kw:
                    names = _literal_names(kw[key])
                    if names is None:
                        continue  # dynamic expression: not checkable
                    for n in names:
                        if n not in all_names:
                            yield Finding(
                                JIT_STATIC.rule_id, path, deco.lineno,
                                f"static_argnames {n!r} is not a "
                                f"parameter of {node.name}"
                                f"({', '.join(all_names)})",
                            )
            for key in ("static_argnums", "donate_argnums"):
                if key in kw:
                    nums = _literal_ints(kw[key])
                    if nums is None:
                        continue
                    for i in nums:
                        if i < 0 or i >= len(positional):
                            yield Finding(
                                JIT_STATIC.rule_id, path, deco.lineno,
                                f"{key} index {i} is out of range for "
                                f"{node.name}'s {len(positional)} "
                                "positional parameter(s)",
                            )
                        elif key == "static_argnums":
                            static_idx.add(i)
            donate = _literal_ints(kw.get("donate_argnums", ast.Constant(
                value=None
            )))
            if donate:
                overlap = static_idx.intersection(donate)
                for i in sorted(overlap):
                    yield Finding(
                        JIT_STATIC.rule_id, path, deco.lineno,
                        f"argument {i} of {node.name} is both static and "
                        "donated — a static arg is part of the compiled "
                        "shape and can never donate its buffer",
                    )


# -------------------------------------------- GL105/GL106 registry drift

# suffixes the prometheus exposition appends (usage sites quote the
# exposition name; declarations quote the family name)
_SERIES_SUFFIXES = ("_total", "_created", "_bucket", "_count", "_sum")
_DECL_CALLS = {"Counter", "Gauge", "Histogram", "Summary"}


def series_base(name: str) -> str:
    for suf in _SERIES_SUFFIXES:
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def declared_series(tree: ast.Module) -> set[str]:
    """Series bases declared via Counter/Gauge/Histogram(...) literals
    in a registry module (stats/metrics.py, stats/cluster.py)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if name.split(".")[-1] in _DECL_CALLS and node.args:
                lit = _str_const(node.args[0])
                if lit:
                    out.add(series_base(lit))
    return out


def declared_stages(tree: ast.Module) -> set[str]:
    """The TRACE_STAGES tuple literal from stats/metrics.py."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "TRACE_STAGES"
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return {
                s for s in (_str_const(e) for e in node.value.elts)
                if s is not None
            }
    return set()


def stage_decl_site(tree: ast.Module) -> tuple[int, list[str]] | None:
    """(line, names) of a module's own `TRACE_STAGES = (...)` tuple
    literal, or None.  GL117 anchors declared-but-never-recorded
    findings on the declaring assignment, and only modules in the
    linted set that themselves declare the tuple anchor findings — so
    linting a loose file set (the corpus) never judges the repo
    registry it can't see."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "TRACE_STAGES"
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            names = [
                s for s in (_str_const(e) for e in node.value.elts)
                if s is not None
            ]
            return node.lineno, names
    return None


def stage_use_literals(tree: ast.Module) -> set[str]:
    """Stage literals recorded at span()/record_span() call sites —
    the same extraction GL106 validates forward, collected per file so
    GL117 can check the reverse direction (a declared stage nothing in
    the tree ever records)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        stage = None
        if name.endswith("span") and not name.endswith("record_span"):
            if node.args:
                stage = _str_const(node.args[0])
        elif name.endswith("record_span") and len(node.args) >= 2:
            stage = _str_const(node.args[1])
        if stage is not None:
            out.add(stage)
    return out


def check_metric_registry(
    tree: ast.Module, path: str, registry: set[str], is_registry_module: bool,
) -> Iterator[Finding]:
    if not registry:
        return  # no registry context (linting a loose file set)
    reported_decls: set[int] = set()  # Constant node ids already flagged
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if (
                name.split(".")[-1] in _DECL_CALLS
                and node.args
                and (_str_const(node.args[0]) or "").startswith("SeaweedFS_")
                and not is_registry_module
            ):
                # one defect, one finding: the walk will reach this
                # Constant again — suppress the usage-literal report
                reported_decls.add(id(node.args[0]))
                yield Finding(
                    METRIC_REGISTRY.rule_id, path, node.lineno,
                    f"series {_str_const(node.args[0])!r} declared outside "
                    "stats/ — register it in stats/metrics.py or "
                    "stats/cluster.py so the drift tests and the README "
                    "table see it",
                )
        lit = _str_const(node)
        if (
            lit
            and id(node) not in reported_decls
            and re.fullmatch(r"SeaweedFS_\w+", lit)
            and series_base(lit) not in registry
        ):
            yield Finding(
                METRIC_REGISTRY.rule_id, path, getattr(node, "lineno", 0),
                f"series literal {lit!r} does not match any series "
                "pre-registered in stats/metrics.py / stats/cluster.py",
            )


def check_stage_registry(
    tree: ast.Module, path: str, stages: set[str]
) -> Iterator[Finding]:
    if not stages:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        stage = None
        if name.endswith("span") and not name.endswith("record_span"):
            if node.args:
                stage = _str_const(node.args[0])
        elif name.endswith("record_span") and len(node.args) >= 2:
            stage = _str_const(node.args[1])
        if stage is not None and stage not in stages:
            yield Finding(
                STAGE_REGISTRY.rule_id, path, node.lineno,
                f"trace stage {stage!r} is not in stats.metrics."
                "TRACE_STAGES — add it there (pre-registered + "
                "README-documented) before recording it",
            )


# --------------------------------------------------- GL108 no-silent-swallow

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted(e) or "" for e in t.elts]
    else:
        names = [dotted(t) or ""]
    return any(n.split(".")[-1] in _BROAD for n in names)


def check_silent_swallow(tree: ast.Module, path: str) -> Iterator[Finding]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
            yield Finding(
                SILENT_SWALLOW.rule_id, path, node.lineno,
                "broad except swallows the error without a log line — "
                "log it (debug is fine, include the trace id when one "
                "is in scope) or narrow the exception type",
            )


# ----------------------------------------------------- GL114 unbounded-rpc

# modules where an unbounded cross-node wait pins a serving/repair/mount
# thread — the EC read path, its repair plane, and the FUSE/operation
# clients.  Control verbs outside this scope are bounded by the stub
# layer's deadline propagation instead (pb/rpc.py attaches the remaining
# budget as the per-call timeout whenever a deadline scope is active).
RPC_SCOPE_PARTS = (
    "seaweedfs_tpu/storage/ec/",
    "seaweedfs_tpu/serving/",
    "seaweedfs_tpu/repair/",
    "seaweedfs_tpu/mount/",
    "seaweedfs_tpu/operation/",
    "seaweedfs_tpu/wdclient/",
    "seaweedfs_tpu/filer/",
    "seaweedfs_tpu/server/volume.py",
    "seaweedfs_tpu/server/filer.py",
    "seaweedfs_tpu/shell/command_ec.py",
    "lint_corpus",
)

# enclosing calls that bound the wrapped RPC themselves: asyncio's
# wait_for and the shared fault-policy retry helper (a lambda passed to
# retry_rpc runs under its wait_for + deadline budget)
_BOUNDED_WRAPPERS = {"wait_for", "retry_rpc"}


def in_rpc_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(part in p for part in RPC_SCOPE_PARTS)


# ------------------------------------------------ GL115 unsharded-device-put

# modules where buffer PLACEMENT is policy: the resident serving layout
# (ops), its mesh helpers (parallel), and the serving plane.  A bare
# jax.device_put(x) here lands on the default device no matter what the
# mesh layout says — it crowds device 0 past its per-device budget and
# the r19 accounting/eviction never sees the bytes where they actually
# are.  Every put must say where: a Sharding (NamedSharding for the
# lane-sharded layout) or an explicit device.  storage/ec's bulk legs
# stay out of scope — the bulk executor feeds single jit calls whose
# inputs the default device is correct for.
DEVICE_PUT_SCOPE_PARTS = (
    "seaweedfs_tpu/ops/",
    "seaweedfs_tpu/serving/",
    "seaweedfs_tpu/parallel/",
    "lint_corpus",
)


def in_device_put_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(part in p for part in DEVICE_PUT_SCOPE_PARTS)


def check_unsharded_device_put(
    tree: ast.Module, path: str
) -> Iterator[Finding]:
    """`jax.device_put(x)` (or `device_put(x)`) without a second
    positional argument or a `device=` keyword in the placement-policy
    scope is a finding — the placement must be explicit."""
    if not in_device_put_scope(path):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        if name.rsplit(".", 1)[-1] != "device_put":
            continue
        if len(node.args) >= 2 or any(
            kw.arg == "device" for kw in node.keywords
        ):
            continue
        yield Finding(
            UNSHARDED_DEVICE_PUT.rule_id, path, node.lineno,
            "jax.device_put without an explicit sharding/device lands "
            "on the default device regardless of the mesh layout — "
            "pass a NamedSharding (lane-sharded residency), the owning "
            "device, or waive a deliberate default-device staging with "
            "a reason",
        )


# ------------------------------------ GL118 process-local-device-assumption

# Direct jax device enumeration in the placement-policy scope.  On a
# multi-process (pod-scale) mesh, jax.devices()/jax.device_count() span
# the POD while jax.local_devices()/jax.local_device_count() cover one
# host — code that sizes a mesh, a budget, or a placement decision off
# whichever it happened to call breaks the moment -ec.mesh.processCount
# goes above 1.  parallel.mesh owns the distinction (local_devices /
# global_devices / serving_mesh / global_serving_mesh and the canonical
# device order); everything in scope must route through it.  mesh.py
# itself is IN scope — its raw calls carry reasoned waivers, which also
# keeps the waiver channel (GL113) honest about them.
_PROCESS_LOCAL_DEVICE_CALLS = frozenset({
    "jax.devices",
    "jax.local_devices",
    "jax.device_count",
    "jax.local_device_count",
})


def check_process_local_device(
    tree: ast.Module, path: str
) -> Iterator[Finding]:
    """Any call of the four raw enumeration entry points (dotted
    `jax.` form) inside the device-put scope is a finding — bare
    imported names are not flagged, since the parallel.mesh helpers
    themselves share those names (`local_devices()` there IS the
    sanctioned call)."""
    if not in_device_put_scope(path):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        if name not in _PROCESS_LOCAL_DEVICE_CALLS:
            continue
        yield Finding(
            PROCESS_LOCAL_DEVICE.rule_id, path, node.lineno,
            f"{name}() is process-local (or pod-global) raw device "
            "enumeration — size meshes and budgets through the "
            "parallel.mesh helpers (local_devices/global_devices/"
            "serving_mesh/global_serving_mesh) so single-process and "
            "pod-scale deployments agree, or waive the deliberate raw "
            "call with a reason",
        )


# ----------------------------------------- GL116 untagged-device-dispatch

# modules where every accelerator dispatch must carry a devledger
# workload class: the resident serving kernels (ops), the serving
# plane, the ingest plane, the repair plane, and the bulk codec.  A
# bare dispatch here bills its busy time to the `untagged` escape-hatch
# class — the per-workload attribution invariant ("ledger sums
# reconcile against the pipeline/codec wall clocks, per CLASS") holds
# only when every primitive call is tagged at the call site.
DISPATCH_SCOPE_PARTS = (
    "seaweedfs_tpu/ops/",
    "seaweedfs_tpu/serving/",
    "seaweedfs_tpu/ingest/",
    "seaweedfs_tpu/repair/",
    "seaweedfs_tpu/storage/ec/",
    "lint_corpus",
)

# the device dispatch primitives (by final dotted name): the jitted
# entry points every accelerator call in the EC stack funnels through
_DISPATCH_PRIMITIVES = {
    "_dispatch_call",        # rs_resident serving reconstruct
    "apply_matrix_device_flat",  # rs_tpu bulk matrix leg
    "_scrub_call",           # per-volume parity scrub
    "_scrub_call_blockdiag",
    "_scrub_all_call",       # multi-volume scrub megakernel
}

# context-manager attrs that establish a ledger class lexically:
# devledger.workload("scrub") / devledger.device(label)
_TAGGING_CTX_ATTRS = {"workload", "device"}


def in_dispatch_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(part in p for part in DISPATCH_SCOPE_PARTS)


def _with_items_tag(node: ast.With) -> bool:
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Call):
            name = dotted(ctx.func) or ""
            if name.rsplit(".", 1)[-1] in _TAGGING_CTX_ATTRS:
                return True
    return False


def _function_tags(fn: ast.AST) -> bool:
    """A function that takes the class as a parameter or consults
    devledger.current_workload() is attribution-aware by design."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    args = fn.args
    for a in (
        args.posonlyargs + args.args + args.kwonlyargs
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        if a.arg == "workload":
            return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr == "current_workload":
            return True
        if isinstance(node, ast.Name) and node.id == "current_workload":
            return True
    return False


def check_untagged_device_dispatch(
    tree: ast.Module, path: str
) -> Iterator[Finding]:
    """Every dispatch-primitive call must be tagged: lexically inside a
    `with devledger.workload(...)/.device(...)` block (the walk stops at
    the enclosing function — a closure dispatched later is not tagged by
    where it was built), carry a `workload=` keyword itself, or sit in a
    function that is attribution-aware (a `workload` parameter or a
    `current_workload` consult)."""
    if not in_dispatch_scope(path):
        return
    parents: dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        if name.rsplit(".", 1)[-1] not in _DISPATCH_PRIMITIVES:
            continue
        if any(kw.arg == "workload" for kw in node.keywords):
            continue
        cur = parents.get(node)
        tagged = False
        while cur is not None:
            if isinstance(cur, ast.With) and _with_items_tag(cur):
                tagged = True
                break
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                tagged = _function_tags(cur)
                break
            cur = parents.get(cur)
        if tagged:
            continue
        yield Finding(
            UNTAGGED_DEVICE_DISPATCH.rule_id, path, node.lineno,
            f"device dispatch {name.rsplit('.', 1)[-1]} carries no "
            "workload class — its busy time lands in the `untagged` "
            "ledger bucket and per-workload attribution leaks; wrap it "
            "in devledger.workload(...)/device(...), pass workload=, "
            "or waive a deliberately unattributed dispatch with a "
            "reason",
        )


def check_unbounded_rpc(
    tree: ast.Module, path: str, rpc_names: set[str]
) -> Iterator[Finding]:
    """Every call whose attribute name is a proto rpc method must carry
    `timeout=` or sit (lexically, lambdas included) inside a bounded
    wrapper call.  Handler DEFINITIONS (servicer methods named after
    rpcs) are not calls and never match; nested function definitions
    stop the ancestor walk — a closure called later is not lexically
    bounded by where it is built."""
    if not rpc_names or not in_rpc_scope(path):
        return
    parents: dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute) or func.attr not in rpc_names:
            continue
        if any(kw.arg == "timeout" for kw in node.keywords):
            continue
        cur = parents.get(node)
        bounded = False
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(cur, ast.Call):
                name = dotted(cur.func) or ""
                if name.rsplit(".", 1)[-1] in _BOUNDED_WRAPPERS:
                    bounded = True
                    break
            cur = parents.get(cur)
        if bounded:
            continue
        yield Finding(
            UNBOUNDED_RPC.rule_id, path, node.lineno,
            f"cross-node RPC {func.attr} has no timeout/deadline — a "
            "hung peer pins this caller forever; pass timeout= (derive "
            "it from faultpolicy.rpc_timeout_s), wrap in "
            "faultpolicy.retry_rpc / asyncio.wait_for, or waive a "
            "deliberately unbounded long-lived stream with a reason",
        )
